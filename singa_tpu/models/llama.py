"""Llama-3 family — the flagship stretch workload (BASELINE.json:11:
"stretch singa.autograd + Graph scheduler to a modern LLM").

Architecture: pre-RMSNorm decoder blocks, rotary position embeddings,
grouped-query attention (n_kv_heads < n_heads), SwiGLU FFN, untied LM
head — all expressed through singa_tpu.autograd operators so the whole
training step (fwd + bwd + optim + collectives) compiles into one XLA
module.

Scaling design (task directive: multi-chip via jax.sharding.Mesh):
SHARD_RULES gives 2-D parallelism out of the box —
  * 'data' axis: batch sharding (DP) via DistOpt/graph executor;
  * 'model' axis: Megatron TP — qkv/gate/up column-parallel, o/down
    row-parallel, embeddings + head vocab/hidden sharded;
  * 'seq' axis: sequence sharding of activations for long context
    (ring attention lives in singa_tpu.ops.ring_attention).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from .. import autograd, layer, model
from ..ops import kv_cache as kv_ops
from ..ops import rope as rope_ops
from ..ops.ring_attention import ring_attention
from ..tensor import Tensor
from ._generate import GenerateMixin
from .transformer import next_token_loss, next_token_loss_fused

__all__ = ["LlamaConfig", "Llama", "LLAMA_SHARD_RULES"]

LLAMA_SHARD_RULES = [
    (r"(q_proj|k_proj|v_proj|gate|up)\.W$", (None, "model")),
    (r"(o_proj|down)\.W$", ("model", None)),
    (r"tok_emb\.table$", (None, "model")),
    (r"lm_head\.W$", (None, "model")),
]


@dataclass
class LlamaConfig:
    vocab_size: int = 128256
    dim: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    ffn_dim: int = 14336
    max_position: int = 8192
    rope_theta: float = 500000.0
    # Llama-3.1-style frequency-dependent RoPE interpolation: 0 = off;
    # e.g. 8.0 extends the usable context ~8x past
    # rope_scaling_original_max_position (the PRETRAINED window the
    # interpolation bands anchor to).  Raise max_position alongside —
    # tables are sized by it, and generate()/training length checks
    # enforce it loudly.
    rope_scaling: float = 0.0
    rope_scaling_original_max_position: int = 8192
    # Mistral-style sliding-window attention: query t attends keys in
    # (t - window, t].  0 = full causal context.  Long sequences run
    # the chunked banded path (ops.attention.banded_attention — O(T*W)
    # memory); incompatible with the 'seq' (ring attention) axis.
    sliding_window: int = 0
    eps: float = 1e-5
    # opt-in chunked fused lm-head+CE loss (never materializes the
    # (B*T, V) logits; autograd.FusedLinearCrossEntropy).  NOTE: with it
    # on, train_one_batch returns (loss, loss) instead of (logits, loss)
    # -- hence opt-in; the bench/dryrun/example enable it explicitly
    fused_loss: bool = False
    # rows per chunk of the fused loss's lax.scan.  Bigger chunks =
    # fewer scan iterations (the tunnel chip taxes every scan iteration
    # ~1 ms — r5 probe 5b) and fewer lm-head weight re-reads, at the
    # cost of a (chunk, V) logits block live per iteration
    # (4096 x 32k x bf16 = 256 MB)
    fused_loss_chunk: int = 512
    # activation checkpointing per transformer block (layer.Remat):
    # block internals recomputed in backward — O(layers) less activation
    # HBM for one extra forward; param paths unchanged
    remat: bool = False
    # pipeline parallelism over the 'pipe' mesh axis: blocks divide into
    # this many stages driven by the GPipe schedule
    # (layer.PipelineStack — global-semantics vmap+roll formulation, so
    # it composes with DistOpt/'data' sharding and remat).  0 = off.
    # Param paths are unchanged, so checkpoints round-trip between
    # pipelined and sequential configs.
    pipeline_stages: int = 0
    # microbatches per step when pipelining (default: = stages)
    pipeline_microbatches: int = 0
    # Mixtral-style MoE: >0 replaces every block's SwiGLU FFN with a
    # top-`moe_top_k` mixture of `num_experts` SwiGLU experts
    # (layer.MoE, expert weights sharded over the 'expert' mesh axis).
    # The Switch balance aux losses are summed into the training loss
    # at weight `moe_aux_weight`.  Incompatible with pipeline_stages
    # (the router's aux side channel cannot replay inside the
    # schedule) — the stack falls back to sequential with a warning.
    # `remat` is likewise inert for MoE blocks: layer.Remat skips
    # layers whose subtree carries a side channel (REMAT_SAFE=False),
    # so a remat+MoE config trains at no-remat activation memory.
    num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01

    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        return LlamaConfig()

    @staticmethod
    def tiny() -> "LlamaConfig":
        return LlamaConfig(vocab_size=256, dim=64, num_layers=2,
                           num_heads=4, num_kv_heads=2, ffn_dim=128,
                           max_position=128, rope_theta=10000.0)

    @staticmethod
    def serve_bench() -> "LlamaConfig":
        """The CPU serve-bench config (bench.py bench_serve and the
        autotune serve sweep share it — the table entry the bench
        resolves must come from a sweep of the SAME architecture):
        big enough that decode reads real weight traffic (the tiny
        test config is per-op-overhead bound, which under-rewards
        batched decode), small enough to stay in a CPU bench budget."""
        return LlamaConfig(vocab_size=1024, dim=256, num_layers=4,
                           num_heads=8, num_kv_heads=4, ffn_dim=688,
                           max_position=128)

    @staticmethod
    def small() -> "LlamaConfig":
        """~110M-param config for single-chip benchmarking."""
        return LlamaConfig(vocab_size=32000, dim=768, num_layers=12,
                           num_heads=12, num_kv_heads=4, ffn_dim=2048,
                           max_position=2048)

    @staticmethod
    def base() -> "LlamaConfig":
        """~0.9B-param flagship bench config for one v5e chip, sized so
        the MXU dominates: honest MFU 0.65 on-chip vs 0.39 for small()
        at the same methodology (r5 flagship sweep,
        tools/flagship_sweep.py).  dim 2048 x 24 layers (1.26B) fails
        the tunnel's compile helper; 16 layers is the largest that
        builds there."""
        return LlamaConfig(vocab_size=32000, dim=2048, num_layers=16,
                           num_heads=16, num_kv_heads=8, ffn_dim=5632,
                           max_position=2048)

    @property
    def head_dim(self) -> int:
        return self.dim // self.num_heads


class _LlamaAttention(layer.Layer):
    def __init__(self, cfg: LlamaConfig, name=None):
        super().__init__(name)
        c = cfg
        self.cfg = c
        self.q_proj = layer.Linear(c.num_heads * c.head_dim, bias=False)
        self.k_proj = layer.Linear(c.num_kv_heads * c.head_dim, bias=False)
        self.v_proj = layer.Linear(c.num_kv_heads * c.head_dim, bias=False)
        self.o_proj = layer.Linear(c.dim, bias=False)
        self._rope = rope_ops.rope_frequencies(
            c.head_dim, c.max_position, c.rope_theta, c.rope_scaling,
            c.rope_scaling_original_max_position)

    def _banded(self, q, k, v, device):
        """Sliding-window attention: causal AND within the last
        `sliding_window` keys.  All backend selection lives in the
        BandedSDPA op (Pallas banded kernel on TPU, chunked O(T*W) jnp
        elsewhere, full-mask reference for degenerate chunkings)."""
        del device
        from ..ops.attention import banded_attention
        from ..parallel import mesh as mesh_mod
        m_ = mesh_mod.current_mesh()
        if m_ is not None and m_.shape.get("seq", 1) > 1:
            raise NotImplementedError(
                "sliding_window attention does not compose with the "
                "'seq' (ring attention) mesh axis — drop the seq axis "
                "or use full causal attention")
        return banded_attention(q, k, v, self.cfg.sliding_window)

    def forward(self, x: Tensor, cache=None, pos=0):
        c = self.cfg
        B, T, _ = x.shape
        cos, sin = self._rope
        q = self.q_proj(x).reshape((B, T, c.num_heads, c.head_dim))
        k = self.k_proj(x).reshape((B, T, c.num_kv_heads, c.head_dim))
        v = self.v_proj(x).reshape((B, T, c.num_kv_heads, c.head_dim))
        q = rope_ops.apply_rope(q, cos, sin, offset=pos)
        k = rope_ops.apply_rope(k, cos, sin, offset=pos)
        windowed = bool(c.sliding_window) and c.sliding_window < T
        if cache is not None:
            ck, cv = kv_ops.update_cache(cache[0], cache[1],
                                         k.data, v.data, pos)
            if isinstance(pos, int) and pos == 0:
                # prefill: attend within the prompt through the regular
                # stack (flash kernel when the shape tiles)
                o = self._banded(q, k, v, x.device) if windowed \
                    else ring_attention(q, k, v, causal=True)
            else:
                o_arr = kv_ops.cached_sdpa(
                    q.data, ck, cv, limit=pos + T,
                    window=c.sliding_window or None)
                o = Tensor(data=o_arr, device=x.device, requires_grad=False)
            out = self.o_proj(o.reshape((B, T, c.num_heads * c.head_dim)))
            return out, (ck, cv)
        if windowed:
            o = self._banded(q, k, v, x.device)
        else:
            # ring attention when a 'seq' mesh axis is installed
            # (cross-chip context parallelism); fused SDPA otherwise
            o = ring_attention(q, k, v, causal=True)
        return self.o_proj(o.reshape((B, T, c.num_heads * c.head_dim)))


class _SwiGLU(layer.Layer):
    def __init__(self, cfg: LlamaConfig, name=None):
        super().__init__(name)
        self.gate = layer.Linear(cfg.ffn_dim, bias=False)
        self.up = layer.Linear(cfg.ffn_dim, bias=False)
        self.down = layer.Linear(cfg.dim, bias=False)

    def forward(self, x):
        return self.down(autograd.silu(self.gate(x)) * self.up(x))


class _LlamaBlock(layer.Layer):
    def __init__(self, cfg: LlamaConfig, name=None):
        super().__init__(name)
        self.attn_norm = layer.RMSNorm(cfg.dim, eps=cfg.eps)
        self.attn = _LlamaAttention(cfg)
        self.ffn_norm = layer.RMSNorm(cfg.dim, eps=cfg.eps)
        if cfg.num_experts:
            self.ffn = layer.MoE(cfg.num_experts, ffn_dim=cfg.ffn_dim,
                                 capacity_factor=cfg.moe_capacity_factor,
                                 top_k=cfg.moe_top_k, act="swiglu")
        else:
            self.ffn = _SwiGLU(cfg)

    def forward(self, x, cache=None, pos=0):
        if cache is not None:
            a, new_cache = self.attn(self.attn_norm(x), cache, pos)
            x = x + a
            x = x + self.ffn(self.ffn_norm(x))
            return x, new_cache
        x = x + self.attn(self.attn_norm(x))
        x = x + self.ffn(self.ffn_norm(x))
        return x


class Llama(GenerateMixin, model.Model):
    SHARD_RULES = LLAMA_SHARD_RULES

    def __init__(self, cfg: Optional[LlamaConfig] = None, **kw):
        super().__init__()
        self.cfg = cfg or LlamaConfig(**kw)
        c = self.cfg
        self.tok_emb = layer.Embedding(c.vocab_size, c.dim)
        blocks = [_LlamaBlock(c) for _ in range(c.num_layers)]
        if c.pipeline_stages:
            # embed and lm head stay outside the pipeline (replicated /
            # 'model'-sharded as usual); only the shape-preserving block
            # stack rides the 'pipe' axis.  remat folds into the stack
            # (per-block jax.checkpoint inside the schedule).
            self.blocks = layer.PipelineStack(
                blocks, stages=c.pipeline_stages,
                n_micro=c.pipeline_microbatches or None, remat=c.remat)
        else:
            if c.remat:
                blocks = [layer.Remat(b) for b in blocks]
            self.blocks = blocks
        self.norm_f = layer.RMSNorm(c.dim, eps=c.eps)
        self.lm_head = layer.Linear(c.vocab_size, bias=False)

    def features(self, ids: Tensor) -> Tensor:
        """Final hidden states (B, T, dim) — everything but the lm head."""
        x = self.tok_emb(ids)
        if isinstance(self.blocks, layer.PipelineStack):
            x = self.blocks(x)
        else:
            for blk in self.blocks:
                x = blk(x)
        return self.norm_f(x)

    def forward(self, ids: Tensor) -> Tensor:
        return self.lm_head(self.features(ids))

    # -- KV-cached decoding (ops/kv_cache.py; VERDICT r2 item 4) ------------
    def init_caches(self, batch: int, max_len: int):
        c = self.cfg
        import jax.numpy as jnp
        dtype = jnp.bfloat16 if self.tok_emb.table.dtype == jnp.bfloat16 \
            else jnp.float32
        return kv_ops.init_cache(c.num_layers, batch, max_len,
                                 c.num_kv_heads, c.head_dim, dtype)

    def forward_cached(self, ids: Tensor, caches, pos):
        x = self.tok_emb(ids)
        new_caches = []
        for blk, cache in zip(self.blocks, caches):
            x, nc = blk(x, cache, pos)
            new_caches.append(nc)
        return self.lm_head(self.norm_f(x)), new_caches

    def _moe_aux_loss(self) -> Optional[Tensor]:
        """Summed router balance losses of every MoE block (None when
        dense or nothing accumulated)."""
        from ..layer import MoE, _walk_layers
        total = None
        for l in _walk_layers(self):
            if isinstance(l, MoE):
                a = l.pop_aux_loss()
                if a is not None:
                    total = a if total is None else total + a
        return total

    def train_one_batch(self, ids: Tensor, labels: Optional[Tensor] = None):
        tgt = labels if labels is not None else ids
        if self.cfg.fused_loss:
            loss = next_token_loss_fused(self.features(ids), self.lm_head,
                                         tgt,
                                         chunk_rows=self.cfg.fused_loss_chunk)
        else:
            logits = self.forward(ids)
            loss = next_token_loss(logits, tgt)
        if self.cfg.num_experts:
            aux = self._moe_aux_loss()
            if aux is not None:
                loss = loss + autograd.mul(aux, self.cfg.moe_aux_weight)
        self.optimizer(loss)
        if self.cfg.fused_loss:
            return loss, loss
        return logits, loss

    def num_params(self) -> int:
        return sum(p.size for p in self.get_params().values())

    def flops_per_token(self, seq_len: int) -> float:
        """Training FLOPs/token ≈ 6·N_matmul + 12·L·dim·T (qk^T and
        probs·v matmuls fwd+bwd at sequence length T) — honest MFU
        accounting, SURVEY.md §7.3 item 6.  N_matmul EXCLUDES the
        token-embedding table: its lookup is a gather, not a matmul
        (same convention as BERT.flops_per_token; r1-r4 included it,
        over-counting ~19% at the `small` config — caught in r5 by
        walking the compiled step's jaxpr, which this formula now
        matches to <1%: utils.flops.jaxpr_matmul_conv_flops).  The
        lm-head stays IN: its projection is a real matmul.  The fused
        chunked loss recomputes the lm-head matmul in backward:
        + 2·dim·V.  For MoE configs N counts only the ACTIVE
        parameters per token (top-k of num_experts expert FFNs), not
        the full expert bank."""
        c = self.cfg
        n = self.num_params()
        if n:
            n -= c.vocab_size * c.dim        # tok_emb gather
        if c.num_experts:
            # each expert FFN: 3 SwiGLU matmuls of dim x ffn_dim.
            # Clamped at 0: before the first forward num_params() is 0
            # (lazy init) and the subtraction would go negative.  The
            # active-FLOPs basis also ignores the capacity-factor
            # over-compute (padded expert slots) — conservative for MFU.
            expert_p = 3 * c.dim * c.ffn_dim
            n = max(n - c.num_layers * (c.num_experts - c.moe_top_k)
                    * expert_p, 0)
        # sliding-window attention computes only min(T, W) keys/query
        attn_span = min(seq_len, c.sliding_window) if c.sliding_window \
            else seq_len
        f = 6 * n + 12 * c.num_layers * c.dim * attn_span
        if c.fused_loss:
            f += 2 * c.dim * c.vocab_size
        return f
