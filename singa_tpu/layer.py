"""singa_tpu.layer — the Layer zoo (capability parity: reference
``singa.layer``; BASELINE.json:5 names the singa.model API stack whose
layers these are).

Semantics kept from the reference surface:
  * layers initialize parameters lazily on first call (shape inference
    from the input), so user code never spells input dims twice;
  * ``get_params()/set_params()`` expose trainable tensors,
    ``get_states()/set_states()`` additionally expose non-trainable
    buffers (e.g. BatchNorm running stats);
  * layers discover sublayers by attribute traversal, in creation order.

TPU-first notes: conv/pool/norm default to NHWC (the layout XLA:TPU maps
onto the MXU); the NCHW entry point is kept for ONNX/reference-style
models and transposes once at the edge.  Parameters are created in f32
and cast per-step for bf16 compute (master weights stay f32 — standard
TPU mixed-precision recipe).
"""

from __future__ import annotations

import math
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import autograd
from . import tensor as tensor_mod
from .tensor import Tensor
from .device import Device

__all__ = [
    "Layer", "Linear", "Conv2d", "SeparableConv2d", "BatchNorm2d",
    "MaxPool2d", "AvgPool2d", "GlobalAvgPool2d", "Flatten", "ReLU",
    "Sigmoid", "Tanh", "Gelu", "SiLU", "LeakyReLU", "Softmax", "Dropout",
    "Embedding", "LayerNorm", "RMSNorm", "RNN", "LSTM",
    "MultiHeadAttention", "MoE", "Remat", "PipelineStack", "Sequential",
    "CrossEntropyLoss", "MSELoss",
]

_name_counter: Dict[str, int] = {}


def _auto_name(prefix: str) -> str:
    n = _name_counter.get(prefix, 0)
    _name_counter[prefix] = n + 1
    return f"{prefix}_{n}" if n else prefix


class Layer:
    """Base layer: lazy init + param/state introspection."""

    def __init__(self, name: Optional[str] = None):
        self.name = name or _auto_name(type(self).__name__.lower())
        self._initialized = False
        self._sublayers: "OrderedDict[str, Layer]" = OrderedDict()
        self._params: "OrderedDict[str, Tensor]" = OrderedDict()
        self._states: "OrderedDict[str, Tensor]" = OrderedDict()  # non-trainable

    # attribute hooks register sublayers / params in declaration order
    def __setattr__(self, key, value):
        if isinstance(value, Layer) and key not in ("_sublayers",):
            self.__dict__.setdefault("_sublayers", OrderedDict())[key] = value
        elif isinstance(value, (list, tuple)) and value and all(
                isinstance(v, Layer) for v in value):
            subs = self.__dict__.setdefault("_sublayers", OrderedDict())
            for i, v in enumerate(value):
                subs[f"{key}.{i}"] = v
        object.__setattr__(self, key, value)

    # -- to implement --------------------------------------------------------
    def initialize(self, *xs):
        """Create parameters from input shapes. Called once lazily."""

    def forward(self, *xs):
        raise NotImplementedError

    def __call__(self, *xs):
        if not self._initialized:
            self.initialize(*xs)
            self._initialized = True
        return self.forward(*xs)

    # -- param/state plumbing -------------------------------------------------
    def register_param(self, name: str, t: Tensor) -> Tensor:
        t.requires_grad = True
        t.stores_grad = True
        t.name = f"{self.name}.{name}"
        self._params[name] = t
        return t

    def register_state(self, name: str, t: Tensor) -> Tensor:
        t.requires_grad = False
        t.stores_grad = False
        t.name = f"{self.name}.{name}"
        self._states[name] = t
        return t

    def get_params(self, prefix: str = "") -> Dict[str, Tensor]:
        """Trainable tensors keyed by *attribute path* (e.g. "fc1.W") —
        stable across instances/processes, so checkpoints round-trip."""
        out = dict()
        for n, p in self._params.items():
            p.name = prefix + n
            out[p.name] = p
        for key, sub in self._sublayers.items():
            out.update(sub.get_params(f"{prefix}{key}."))
        return out

    def set_params(self, params: Dict[str, Tensor], prefix: str = "") -> None:
        for n, p in self._params.items():
            full = prefix + n
            if full in params:
                src = params[full]
                p.copy_from(src if isinstance(src, Tensor) else np.asarray(src))
        for key, sub in self._sublayers.items():
            sub.set_params(params, f"{prefix}{key}.")

    def get_states(self, prefix: str = "") -> Dict[str, Tensor]:
        out = dict(self.get_params(prefix))
        out.update(self._get_buffers(prefix))
        return out

    def _get_buffers(self, prefix: str = "") -> Dict[str, Tensor]:
        out = dict()
        for n, s in self._states.items():
            s.name = prefix + n
            out[s.name] = s
        for key, sub in self._sublayers.items():
            out.update(sub._get_buffers(f"{prefix}{key}."))
        return out

    # name-PRESERVING traversals: get_params/_get_buffers rewrite each
    # tensor's .name from the prefix — callers that only need the
    # tensors (e.g. Remat's per-step param threading) must not clobber
    # the executor-assigned full paths that key optimizer state
    def _param_list(self) -> List[Tensor]:
        out = list(self._params.values())
        for sub in self._sublayers.values():
            out.extend(sub._param_list())
        return out

    def _buffer_list(self) -> List[Tensor]:
        out = list(self._states.values())
        for sub in self._sublayers.values():
            out.extend(sub._buffer_list())
        return out

    def set_states(self, states: Dict[str, Tensor], prefix: str = "") -> None:
        self.set_params(states, prefix)
        for n, s in self._states.items():
            full = prefix + n
            if full in states:
                src = states[full]
                s.copy_from(src if isinstance(src, Tensor) else np.asarray(src))
        for key, sub in self._sublayers.items():
            sub.set_states(states, f"{prefix}{key}.")

    def to_device(self, dev: Device) -> "Layer":
        for p in self._params.values():
            p.to_device(dev)
        for s in self._states.values():
            s.to_device(dev)
        for sub in self._sublayers.values():
            sub.to_device(dev)
        if hasattr(self, "device"):
            self.device = dev
        return self

    def sublayers(self) -> List["Layer"]:
        return list(self._sublayers.values())

    def __repr__(self):
        return f"<{type(self).__name__} {self.name}>"


# ---------------------------------------------------------------------------
# initializers (He / Xavier, f32 master weights)
# ---------------------------------------------------------------------------

def _he_normal(shape, fan_in, dev) -> Tensor:
    std = math.sqrt(2.0 / max(1, fan_in))
    t = Tensor(shape, dev, np.float32)
    return t.gaussian(0.0, std)


def _xavier_uniform(shape, fan_in, fan_out, dev) -> Tensor:
    a = math.sqrt(6.0 / max(1, fan_in + fan_out))
    t = Tensor(shape, dev, np.float32)
    return t.uniform(-a, a)


# ---------------------------------------------------------------------------
# core layers
# ---------------------------------------------------------------------------

class Linear(Layer):
    def __init__(self, out_features: int, in_features: Optional[int] = None,
                 bias: bool = True, name=None):
        super().__init__(name)
        # reference also allows Linear(in, out) positional style
        if in_features is not None and in_features > 0 and out_features > 0 \
                and isinstance(in_features, int):
            pass
        self.out_features = out_features
        self.in_features = in_features
        self.bias = bias

    def initialize(self, x: Tensor):
        in_f = self.in_features or x.shape[-1]
        self.in_features = in_f
        dev = x.device
        self.W = self.register_param(
            "W", _xavier_uniform((in_f, self.out_features), in_f,
                                 self.out_features, dev))
        if self.bias:
            self.b = self.register_param(
                "b", Tensor((self.out_features,), dev, np.float32))

    def forward(self, x: Tensor) -> Tensor:
        w = _maybe_cast(self.W, x)
        if self.bias:
            return autograd.linear(x, w, _maybe_cast(self.b, x))
        return autograd.linear(x, w)


def _maybe_cast(p: Tensor, x: Tensor) -> Tensor:
    """Cast f32 master param to the compute dtype of x (bf16 on TPU)."""
    if p.dtype == x.dtype:
        return p
    return autograd.cast(p, x.dtype)


class Conv2d(Layer):
    """Conv layer; data_format 'NHWC' (TPU-native) or 'NCHW' (reference/ONNX)."""

    def __init__(self, out_channels: int, kernel_size, in_channels=None,
                 stride=1, padding=0, bias=True, groups=1, dilation=1,
                 data_format="NHWC", name=None):
        super().__init__(name)
        self.out_channels = out_channels
        self.in_channels = in_channels
        self.kernel_size = (kernel_size, kernel_size) if isinstance(
            kernel_size, int) else tuple(kernel_size)
        self.stride = stride
        self.padding = padding
        self.use_bias = bias
        self.groups = groups
        self.dilation = dilation
        self.data_format = data_format

    def initialize(self, x: Tensor):
        c_axis = -1 if self.data_format == "NHWC" else 1
        in_c = self.in_channels or x.shape[c_axis]
        # layout tripwire: a (N, 3, H, W) image fed to an NHWC conv is
        # silently read as a 3-pixel-tall W-channel image — shapes stay
        # consistent, loss still falls, and the network is garbage
        # (exactly what the r1-r4 ResNet bench measured).  Warn loudly
        # when the other axis looks far more channel-like.
        if len(x.shape) == 4 and self.in_channels is None:
            other = x.shape[1 if self.data_format == "NHWC" else -1]
            # the spatial dim adjacent to the claimed channel axis: if
            # the input really is the OTHER layout, the claimed-channel
            # axis is a spatial dim and (for the common square-image
            # case) equals its neighbour
            neighbor = x.shape[-2 if self.data_format == "NHWC" else 2]
            # 1/3 = gray/RGB; deeper feature maps legitimately shrink to
            # tiny spatial dims, so 2/4 etc. stay silent.  Requiring the
            # suspect axis to LOOK spatial (== its neighbour) silences
            # the false positive on genuine NHWC inputs with spatial
            # height 1 or 3 and many channels, e.g. (N, 1, W, C)
            # spectrogram rows (ADVICE r5).
            if other in (1, 3) and in_c > 8 and in_c == neighbor:
                import warnings
                warnings.warn(
                    f"Conv2d(data_format={self.data_format!r}) sees input "
                    f"shape {tuple(x.shape)}: axis {c_axis} ({in_c} "
                    f"channels) looks spatial while the other layout's "
                    f"channel axis has {other} — is the input "
                    f"{'NCHW' if self.data_format == 'NHWC' else 'NHWC'}?",
                    stacklevel=2)
        self.in_channels = in_c
        kh, kw = self.kernel_size
        fan_in = in_c * kh * kw // self.groups
        dev = x.device
        # HWIO kernel layout (XLA native)
        self.W = self.register_param(
            "W", _he_normal((kh, kw, in_c // self.groups, self.out_channels),
                            fan_in, dev))
        if self.use_bias:
            self.b = self.register_param(
                "b", Tensor((self.out_channels,), dev, np.float32))

    def forward(self, x: Tensor) -> Tensor:
        if self.data_format == "NCHW":
            x = autograd.transpose(x, (0, 2, 3, 1))
        w = _maybe_cast(self.W, x)
        b = _maybe_cast(self.b, x) if self.use_bias else None
        y = autograd.conv2d(x, w, b, self.stride, self.padding,
                            self.groups, self.dilation)
        if self.data_format == "NCHW":
            y = autograd.transpose(y, (0, 3, 1, 2))
        return y


class SeparableConv2d(Layer):
    def __init__(self, out_channels, kernel_size, in_channels=None, stride=1,
                 padding=0, bias=False, data_format="NHWC", name=None):
        super().__init__(name)
        self.depthwise = Conv2d(0, kernel_size, stride=stride, padding=padding,
                                bias=bias, data_format=data_format)
        self.pointwise = Conv2d(out_channels, 1, bias=bias,
                                data_format=data_format)
        self.data_format = data_format

    def initialize(self, x: Tensor):
        c_axis = -1 if self.data_format == "NHWC" else 1
        in_c = x.shape[c_axis]
        self.depthwise.out_channels = in_c
        self.depthwise.groups = in_c

    def forward(self, x: Tensor) -> Tensor:
        return self.pointwise(self.depthwise(x))


class BatchNorm2d(Layer):
    """BatchNorm with running stats kept as layer *states* so the compiled
    training step threads them functionally (SURVEY.md §7.3 item 2)."""

    def __init__(self, num_features=None, momentum=0.9, eps=1e-5,
                 data_format="NHWC", name=None):
        super().__init__(name)
        self.num_features = num_features
        self.momentum = momentum
        self.eps = eps
        self.data_format = data_format

    def initialize(self, x: Tensor):
        c_axis = -1 if self.data_format == "NHWC" else 1
        c = self.num_features or x.shape[c_axis]
        self.num_features = c
        dev = x.device
        self.gamma = self.register_param("gamma", Tensor((c,), dev, np.float32).set_value(1.0))
        self.beta = self.register_param("beta", Tensor((c,), dev, np.float32))
        self.running_mean = self.register_state("running_mean", Tensor((c,), dev, np.float32))
        self.running_var = self.register_state("running_var", Tensor((c,), dev, np.float32).set_value(1.0))

    def forward(self, x: Tensor) -> Tensor:
        nchw = self.data_format == "NCHW"
        if nchw:
            x = autograd.transpose(x, (0, 2, 3, 1))
        axes = (0, 1, 2) if x.ndim == 4 else (0,)
        if autograd.is_training():
            xf = autograd.cast(x, np.float32) if x.dtype != np.float32 else x
            mean = autograd.reduce_mean(xf, axes)
            var = autograd.reduce_mean(autograd.mul(xf, xf), axes) - autograd.mul(mean, mean)
            # running-stat update: functional rebinding, threaded out of jit
            m = self.momentum
            self.running_mean.data = (m * self.running_mean.data
                                      + (1 - m) * jax.lax.stop_gradient(mean.data))
            self.running_var.data = (m * self.running_var.data
                                     + (1 - m) * jax.lax.stop_gradient(var.data))
        else:
            mean, var = self.running_mean, self.running_var
        y = autograd.batchnorm(x, _maybe_cast(self.gamma, x),
                               _maybe_cast(self.beta, x),
                               _maybe_cast(mean, x), _maybe_cast(var, x),
                               self.eps)
        if nchw:
            y = autograd.transpose(y, (0, 3, 1, 2))
        return y


class MaxPool2d(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NHWC", name=None):
        super().__init__(name)
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding
        self.data_format = data_format

    def forward(self, x):
        if self.data_format == "NCHW":
            x = autograd.transpose(x, (0, 2, 3, 1))
        y = autograd.max_pool2d(x, self.kernel_size, self.stride, self.padding)
        if self.data_format == "NCHW":
            y = autograd.transpose(y, (0, 3, 1, 2))
        return y


class AvgPool2d(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NHWC", name=None):
        super().__init__(name)
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding
        self.data_format = data_format

    def forward(self, x):
        if self.data_format == "NCHW":
            x = autograd.transpose(x, (0, 2, 3, 1))
        y = autograd.avg_pool2d(x, self.kernel_size, self.stride, self.padding)
        if self.data_format == "NCHW":
            y = autograd.transpose(y, (0, 3, 1, 2))
        return y


class GlobalAvgPool2d(Layer):
    def __init__(self, data_format="NHWC", name=None):
        super().__init__(name)
        self.data_format = data_format

    def forward(self, x):
        axes = (1, 2) if self.data_format == "NHWC" else (2, 3)
        return autograd.reduce_mean(x, axes)


class Flatten(Layer):
    def __init__(self, start_axis=1, name=None):
        super().__init__(name)
        self.start_axis = start_axis

    def forward(self, x):
        return autograd.flatten(x, self.start_axis)


class ReLU(Layer):
    def forward(self, x):
        return autograd.relu(x)


class Sigmoid(Layer):
    def forward(self, x):
        return autograd.sigmoid(x)


class Tanh(Layer):
    def forward(self, x):
        return autograd.tanh(x)


class Gelu(Layer):
    def __init__(self, approximate: bool = True, name=None):
        super().__init__(name)
        self.approximate = approximate

    def forward(self, x):
        return autograd.gelu(x, self.approximate)


class SiLU(Layer):
    def forward(self, x):
        return autograd.silu(x)


class LeakyReLU(Layer):
    def __init__(self, slope=0.01, name=None):
        super().__init__(name)
        self.slope = slope

    def forward(self, x):
        return autograd.leakyrelu(x, self.slope)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__(name)
        self.axis = axis

    def forward(self, x):
        return autograd.softmax(x, self.axis)


class Dropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__(name)
        self.p = p

    def forward(self, x):
        return autograd.dropout(x, self.p)


class Embedding(Layer):
    def __init__(self, vocab_size, embed_dim, name=None):
        super().__init__(name)
        self.vocab_size, self.embed_dim = vocab_size, embed_dim

    def initialize(self, ids: Tensor):
        dev = ids.device
        self.table = self.register_param(
            "table", Tensor((self.vocab_size, self.embed_dim), dev,
                            np.float32).gaussian(0.0, 0.02))

    def forward(self, ids: Tensor) -> Tensor:
        out = autograd.embedding(self.table, ids)
        # master table is f32; activations run in the device compute dtype
        # (bf16 on TPU) — cast after the gather so only B*T*D bytes move
        dev = ids.device
        dt = getattr(dev, "default_dtype", None)
        if dt is not None and np.dtype(dt) != np.dtype(np.float32):
            out = autograd.cast(out, dt)
        return out


class LayerNorm(Layer):
    def __init__(self, dim=None, eps=1e-5, name=None):
        super().__init__(name)
        self.dim, self.eps = dim, eps

    def initialize(self, x: Tensor):
        d = self.dim or x.shape[-1]
        self.dim = d
        dev = x.device
        self.gamma = self.register_param("gamma", Tensor((d,), dev, np.float32).set_value(1.0))
        self.beta = self.register_param("beta", Tensor((d,), dev, np.float32))

    def forward(self, x):
        return autograd.layernorm(x, _maybe_cast(self.gamma, x),
                                  _maybe_cast(self.beta, x), self.eps)


class RMSNorm(Layer):
    def __init__(self, dim=None, eps=1e-6, name=None):
        super().__init__(name)
        self.dim, self.eps = dim, eps

    def initialize(self, x: Tensor):
        d = self.dim or x.shape[-1]
        self.dim = d
        self.gamma = self.register_param(
            "gamma", Tensor((d,), x.device, np.float32).set_value(1.0))

    def forward(self, x):
        return autograd.rmsnorm(x, _maybe_cast(self.gamma, x), self.eps)


# ---------------------------------------------------------------------------
# recurrent layers — lax.scan over time (XLA-friendly control flow; no
# Python loops in the hot path)
# ---------------------------------------------------------------------------

class _ScanRNNOp(autograd.Operator):
    """Generic scanned RNN cell op; the cell body is a pure function so the
    whole unrolled-in-time computation lowers to one lax.scan.

    `kind`/`hidden` identify the cell for the ONNX exporter (sonnx
    emits a real LSTM/RNN node with the weight layout converted)."""

    def __init__(self, cell_fn, h0_fn, kind: str = "", hidden: int = 0):
        super().__init__()
        self.cell_fn = cell_fn
        self.h0_fn = h0_fn
        self.kind = kind
        self.hidden = hidden

    def fwd(self, x, *weights):
        # x: (B, T, D) -> scan over T
        carry0 = self.h0_fn(x)

        def step(carry, xt):
            new_carry, out = self.cell_fn(carry, xt, weights)
            return new_carry, out

        xs = jnp.swapaxes(x, 0, 1)  # (T, B, D)
        _, ys = jax.lax.scan(step, carry0, xs)
        return jnp.swapaxes(ys, 0, 1)  # (B, T, H)


class RNN(Layer):
    """Vanilla tanh RNN (reference singa.autograd RNN parity)."""

    def __init__(self, hidden_size, name=None):
        super().__init__(name)
        self.hidden_size = hidden_size

    def initialize(self, x: Tensor):
        d, h = x.shape[-1], self.hidden_size
        dev = x.device
        self.Wx = self.register_param("Wx", _xavier_uniform((d, h), d, h, dev))
        self.Wh = self.register_param("Wh", _xavier_uniform((h, h), h, h, dev))
        self.b = self.register_param("b", Tensor((h,), dev, np.float32))

    def forward(self, x: Tensor) -> Tensor:
        h = self.hidden_size

        def cell(carry, xt, weights):
            wx, wh, b = weights
            nh = jnp.tanh(xt @ wx + carry @ wh + b)
            return nh, nh

        def h0(xa):
            return jnp.zeros((xa.shape[0], h), xa.dtype)

        return _ScanRNNOp(cell, h0, "RNN", h)(x, _maybe_cast(self.Wx, x),
                                              _maybe_cast(self.Wh, x),
                                              _maybe_cast(self.b, x))


class LSTM(Layer):
    def __init__(self, hidden_size, name=None):
        super().__init__(name)
        self.hidden_size = hidden_size

    def initialize(self, x: Tensor):
        d, h = x.shape[-1], self.hidden_size
        dev = x.device
        self.Wx = self.register_param("Wx", _xavier_uniform((d, 4 * h), d, 4 * h, dev))
        self.Wh = self.register_param("Wh", _xavier_uniform((h, 4 * h), h, 4 * h, dev))
        self.b = self.register_param("b", Tensor((4 * h,), dev, np.float32))

    def forward(self, x: Tensor) -> Tensor:
        h = self.hidden_size

        def cell(carry, xt, weights):
            wx, wh, b = weights
            hp, cp = carry
            z = xt @ wx + hp @ wh + b
            i, f, g, o = jnp.split(z, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c = f * cp + i * g
            nh = o * jnp.tanh(c)
            return (nh, c), nh

        def h0(xa):
            z = jnp.zeros((xa.shape[0], h), xa.dtype)
            return (z, z)

        return _ScanRNNOp(cell, h0, "LSTM", h)(x, _maybe_cast(self.Wx, x),
                                               _maybe_cast(self.Wh, x),
                                               _maybe_cast(self.b, x))


class MultiHeadAttention(Layer):
    """Standard MHA; uses the fused attention op from singa_tpu.ops (pallas
    flash attention on TPU, reference jnp path elsewhere)."""

    def __init__(self, num_heads, embed_dim=None, causal=False, name=None):
        super().__init__(name)
        self.num_heads = num_heads
        self.embed_dim = embed_dim
        self.causal = causal

    def initialize(self, x: Tensor, *rest):
        d = self.embed_dim or x.shape[-1]
        self.embed_dim = d
        self.q_proj = Linear(d, d, bias=True)
        self.k_proj = Linear(d, d, bias=True)
        self.v_proj = Linear(d, d, bias=True)
        self.out_proj = Linear(d, d, bias=True)

    def forward(self, x: Tensor, mask: Optional[Tensor] = None,
                cache=None, pos=0):
        from .ops import attention as attn_ops
        B, T, D = x.shape
        H = self.num_heads
        hd = D // H
        q = self.q_proj(x).reshape((B, T, H, hd))
        k = self.k_proj(x).reshape((B, T, H, hd))
        v = self.v_proj(x).reshape((B, T, H, hd))
        if cache is not None:
            from .ops import kv_cache as kv_ops
            ck, cv = kv_ops.update_cache(cache[0], cache[1],
                                         k.data, v.data, pos)
            if isinstance(pos, int) and pos == 0:
                o = attn_ops.attention(q, k, v, causal=self.causal, mask=mask)
            else:
                m_arr = mask.data if isinstance(mask, Tensor) else mask
                o_arr = kv_ops.cached_sdpa(q.data, ck, cv, limit=pos + T,
                                           mask=m_arr)
                o = Tensor(data=o_arr, device=x.device, requires_grad=False)
            return self.out_proj(o.reshape((B, T, D))), (ck, cv)
        o = attn_ops.attention(q, k, v, causal=self.causal, mask=mask)
        return self.out_proj(o.reshape((B, T, D)))


class _MoEOp(autograd.Operator):
    def __init__(self, cf, top_k=1, swiglu=False, dispatch_mode="auto"):
        super().__init__()
        self.cf = cf
        self.top_k = top_k
        self.swiglu = swiglu
        self.dispatch_mode = dispatch_mode

    def fwd(self, xa, rw, wi, wo, *wg):
        from .ops.moe import moe_forward
        out, aux = moe_forward(xa, rw, wi, wo, self.cf, return_aux=True,
                               top_k=self.top_k,
                               w_gate=wg[0] if self.swiglu else None,
                               dispatch_mode=self.dispatch_mode)
        return out, aux


class MoE(Layer):
    """Top-1 mixture-of-experts FFN (ops/moe.py — GShard/Switch static
    dispatch).  Stacked expert weights carry a leading E axis; the
    layer declares SHARD_RULES sharding it over the 'expert' mesh axis
    (the executor merges sublayer rules, so models need not repeat
    them) — with EP the dispatch/combine einsums become all-to-alls.

    The router's load-balance auxiliary losses accumulate across
    *training-mode* calls (eval and compile-time dry runs don't
    accumulate — an init-trace entry would leak a dead tracer);
    `pop_aux_loss()` returns their sum and resets — add it to the
    training loss once per step."""

    SHARD_RULES = [
        (r"\.(w_in|w_out|w_gate)$", ("expert", None, None)),
    ]
    # the aux-loss accumulator is a side channel: a forward replayed
    # inside a jax.checkpoint region would leak its tracer (and drop
    # the router's balance-loss gradient) — layer.Remat must bypass
    REMAT_SAFE = False

    def __init__(self, num_experts: int, ffn_dim: int,
                 capacity_factor: float = 1.25, top_k: int = 1,
                 act: str = "relu", dispatch_mode: str = "auto", name=None):
        super().__init__(name)
        if not 1 <= top_k <= num_experts:
            raise ValueError(
                f"top_k={top_k} outside [1, num_experts={num_experts}]")
        if act not in ("relu", "swiglu"):
            raise ValueError(f"MoE act must be relu or swiglu, got {act!r}")
        if dispatch_mode not in ("auto", "scatter", "einsum"):
            raise ValueError(f"dispatch_mode must be auto/scatter/einsum, "
                             f"got {dispatch_mode!r}")
        self.num_experts = num_experts
        self.ffn_dim = ffn_dim
        self.capacity_factor = capacity_factor
        self.top_k = top_k
        self.act = act
        # explicit token-movement choice (ops/moe.py docstring): 'auto'
        # resolves the global mesh at trace time — pass scatter/einsum
        # to pin the form independent of when the mesh is installed
        self.dispatch_mode = dispatch_mode
        self._aux_losses: List[Tensor] = []

    def initialize(self, x: Tensor):
        d = x.shape[-1]
        e, h = self.num_experts, self.ffn_dim
        dev = x.device
        self.router = self.register_param(
            "router", _xavier_uniform((d, e), d, e, dev))
        self.w_in = self.register_param(
            "w_in", Tensor((e, d, h), dev, np.float32).gaussian(
                0.0, (2.0 / (d + h)) ** 0.5))
        self.w_out = self.register_param(
            "w_out", Tensor((e, h, d), dev, np.float32).gaussian(
                0.0, (2.0 / (d + h)) ** 0.5))
        if self.act == "swiglu":
            self.w_gate = self.register_param(
                "w_gate", Tensor((e, d, h), dev, np.float32).gaussian(
                    0.0, (2.0 / (d + h)) ** 0.5))

    def forward(self, x: Tensor) -> Tensor:
        # router stays f32 master: moe_forward computes routing in f32
        extra = (self.w_gate,) if self.act == "swiglu" else ()
        out, aux = _MoEOp(self.capacity_factor, self.top_k,
                          self.act == "swiglu", self.dispatch_mode)(
            x, self.router, self.w_in, self.w_out, *extra)
        # accumulate only in training: eval/compile-time dry runs must
        # not leave stale entries (an init-trace tracer here would crash
        # the first real pop_aux_loss)
        if autograd.is_training():
            self._aux_losses.append(aux)
        return out

    @property
    def aux_loss(self) -> Optional[Tensor]:
        """Most recent *training* call's balance loss (eval forwards do
        not record; see pop_aux_loss for the accumulated per-step sum)."""
        return self._aux_losses[-1] if self._aux_losses else None

    def pop_aux_loss(self) -> Optional[Tensor]:
        """Sum of balance losses since the last pop; resets the store."""
        if not self._aux_losses:
            return None
        total = self._aux_losses[0]
        for a in self._aux_losses[1:]:
            total = total + a
        self._aux_losses = []
        return total


class _RematOp(autograd.Operator):
    """Runs a wrapped layer's forward as a PURE jax function under
    jax.checkpoint: the jax.vjp-derived backward then saves only the
    op's inputs and recomputes the block's internals — activation
    memory O(block inputs) instead of O(block internals).

    `extras`: trailing non-differentiable forward args (e.g. an
    attention mask) closed over by the pure fn — they become jaxpr
    constants the checkpoint keeps as residuals."""

    def __init__(self, inner, extras=()):
        super().__init__()
        self.inner = inner
        self.extras = extras

    def fwd(self, x, *param_leaves):
        inner = self.inner
        extras = self.extras
        # reserve a PRNG key for the block's internal RNG (dropout)
        # OUTSIDE the checkpoint: splits inside the checkpoint trace
        # would otherwise write checkpoint-scoped tracers into the
        # global key, crashing the next consumer after the trace closes
        blk_key = tensor_mod._next_key()

        def pure(x_a, *pl):
            ptens = inner._param_list()        # name-preserving
            saved = [(t.data, t.requires_grad, t.stores_grad)
                     for t in ptens]
            saved_key = tensor_mod._rng_key
            try:
                tensor_mod._rng_key = blk_key
                for t, a in zip(ptens, pl):
                    # requires_grad=False: inner ops run plain fwd (the
                    # outer vjp over the whole block owns the gradient)
                    t.data = a
                    t.requires_grad = False
                    t.stores_grad = False
                xt = Tensor(data=x_a, requires_grad=False)
                out = inner.forward(xt, *extras)
                return out.data
            finally:
                tensor_mod._rng_key = saved_key
                for t, (d, rg, sg) in zip(ptens, saved):
                    t.data = d
                    t.requires_grad = rg
                    t.stores_grad = sg

        return jax.checkpoint(pure)(x, *param_leaves)


class Remat(Layer):
    """Activation checkpointing: wrap a (stateless) sublayer so its
    internals are recomputed during backward instead of saved —
    `layer.Remat(block)` trades one extra forward for O(layer) less
    activation HBM, the standard deep-transformer memory lever.

    The wrapped layer must be buffer-free (e.g. no BatchNorm running
    stats: the forward runs again in backward and must be side-effect
    free); such layers fall back to the plain call with a warning.
    Parameter paths are UNCHANGED (the wrapper segment is transparent),
    so checkpoints and shard rules work identically with or without
    the wrapper."""

    def __init__(self, inner: Layer, name=None):
        super().__init__(name)
        self.inner = inner

    # parameter/state paths pass through unchanged: Remat(block) and the
    # bare block have identical checkpoints and shard-rule matches
    def get_params(self, prefix: str = "") -> Dict[str, Tensor]:
        return self.inner.get_params(prefix)

    def set_params(self, params, prefix: str = "") -> None:
        self.inner.set_params(params, prefix)

    def _get_buffers(self, prefix: str = "") -> Dict[str, Tensor]:
        return self.inner._get_buffers(prefix)

    def set_states(self, states, prefix: str = "") -> None:
        self.inner.set_states(states, prefix)

    def forward(self, x: Tensor, *rest):
        if not self.inner._initialized:
            # first call materializes params through the normal lazy
            # path (outside any checkpoint region)
            return self.inner(x, *rest)
        if not autograd.is_training():
            return self.inner(x, *rest)   # nothing to save in eval
        unsafe = [l for l in _walk_layers(self.inner)
                  if not getattr(type(l), "REMAT_SAFE", True)]
        if unsafe or self.inner._buffer_list():
            import warnings
            what = ("side-channel layers "
                    f"({', '.join(type(l).__name__ for l in unsafe)})"
                    if unsafe else "non-trainable buffers")
            warnings.warn(
                f"Remat({self.inner.name}) skipped: wrapped layer has "
                f"{what} (the forward replayed in backward must be "
                f"side-effect free)", stacklevel=2)
            return self.inner(x, *rest)
        # trailing args (attention masks, ...) thread through the
        # checkpoint as closed-over constants when non-differentiable;
        # anything gradient-carrying or structured (KV caches) bypasses
        for r in rest:
            if not (r is None or (isinstance(r, Tensor)
                                  and not r.requires_grad)):
                import warnings
                warnings.warn(
                    f"Remat({self.inner.name}) bypassed for a call with "
                    f"unsupported extra arg {type(r).__name__}",
                    stacklevel=2)
                return self.inner(x, *rest)
        return _RematOp(self.inner, tuple(rest))(
            x, *self.inner._param_list())


def _walk_layers(l):
    yield l
    for s in l._sublayers.values():
        yield from _walk_layers(s)


class _PipelineOp(autograd.Operator):
    """GPipe over the 'pipe' mesh axis, expressed as ONE global-semantics
    pure function (the TPU-native formulation — no shard_map):

      * every block's params are stacked in-graph onto a leading
        (stages, blocks_per_stage) axis and pinned to P('pipe') with a
        sharding constraint, so each pipe rank materializes only its
        stage's weights;
      * each schedule tick runs `vmap` over the stage axis (all stages
        compute concurrently on different microbatches — exactly the
        per-rank stage step of parallel/pipeline.py's shard_map gpipe);
      * the activation hand-off is `jnp.roll` along the 'pipe'-sharded
        stage axis, which GSPMD lowers to a one-hop collective-permute
        over ICI;
      * `lax.scan` drives the n_micro + S - 1 ticks, and because scan
        and roll differentiate, the jax.vjp-derived Operator backward IS
        the reverse pipeline schedule (GPipe backward) for free.

    Bubble ticks are masked to zero so they contribute nothing to
    gradients.  Block internals optionally run under jax.checkpoint
    (remat), composing PP with activation checkpointing.
    """

    def __init__(self, stack: "PipelineStack", extras=()):
        super().__init__()
        self.stack = stack
        # non-grad, batch-leading extra arrays (e.g. a (B,1,1,T) padding
        # mask): microbatched alongside x and gathered per stage per
        # tick, so masked transformer blocks pipeline too
        self.extras = tuple(extras)

    def fwd(self, x, *param_leaves):
        import jax.numpy as jnp

        from .parallel import mesh as mesh_mod

        st = self.stack
        blocks = st.inner
        L, S, M = len(blocks), st.stages, st.n_micro
        k = L // S
        template = blocks[0]
        tpl = template._param_list()
        n_per = len(tpl)
        blk_key = tensor_mod._next_key()
        mesh = mesh_mod.current_mesh()
        extras = self.extras

        def constrain(a, *axes):
            if mesh is None:
                return a
            spec = mesh_mod.P(*[ax if (ax in mesh.shape
                                       and mesh.shape[ax] > 1) else None
                                for ax in axes])
            return jax.lax.with_sharding_constraint(
                a, mesh_mod.NamedSharding(mesh, spec))

        def constrain_stacked(a, tpl_tensor):
            """Stacked (S, k, *param) weights: stage axis over 'pipe',
            trailing param dims under the model's SHARD_RULES (same TP
            layout the executor pinned on the unstacked params — no
            per-step all-gather of TP shards).  _pipe_live() guarantees
            the mesh exists with pipe == stages > 1 whenever this op
            runs."""
            from .parallel import spmd as spmd_mod
            rules = spmd_mod.current_trace_rules()
            pspec = ()
            name = getattr(tpl_tensor, "name", "") or ""
            if rules and name:
                pspec = tuple(spmd_mod.spec_for(
                    name, tuple(tpl_tensor.data.shape), rules, mesh))
            spec = mesh_mod.P("pipe", None, *pspec)
            return jax.lax.with_sharding_constraint(
                a, mesh_mod.NamedSharding(mesh, spec))

        def apply_block(leaves, h, *ex):
            saved = [(t.data, t.requires_grad, t.stores_grad) for t in tpl]
            saved_key = tensor_mod._rng_key
            try:
                tensor_mod._rng_key = blk_key
                for t, a in zip(tpl, leaves):
                    t.data = a
                    t.requires_grad = False
                    t.stores_grad = False
                out = template.forward(
                    Tensor(data=h, requires_grad=False),
                    *(Tensor(data=e, requires_grad=False) for e in ex))
                return out.data
            finally:
                tensor_mod._rng_key = saved_key
                for t, (d, rg, sg) in zip(tpl, saved):
                    t.data = d
                    t.requires_grad = rg
                    t.stores_grad = sg

        if st.remat:
            apply_block = jax.checkpoint(apply_block)

        def pure(x_a, *leaves):
            B = x_a.shape[0]
            if B % M:
                raise ValueError(
                    f"batch {B} not divisible by n_micro={M}")
            mb = B // M
            # stack blocks-major flat leaves into per-param
            # (S, k, *param_shape) arrays: stage axis sharded over
            # 'pipe', param dims under the model's TP rules
            stacked = tuple(
                constrain_stacked(
                    jnp.stack([leaves[b * n_per + j] for b in range(L)])
                    .reshape((S, k) + leaves[j].shape), tpl[j])
                for j in range(n_per))
            x_micro = x_a.reshape((M, mb) + x_a.shape[1:])
            ex_micro = tuple(e.reshape((M, mb) + e.shape[1:])
                             for e in extras)

            def stage_fn(stage_leaves, h, *ex):
                for i in range(k):
                    h = apply_block([a[i] for a in stage_leaves], h, *ex)
                return h

            vstage = jax.vmap(stage_fn,
                              in_axes=(0, 0) + (0,) * len(extras))
            act_shape = (mb,) + x_a.shape[1:]
            bufs0 = jnp.zeros((S,) + act_shape, x_a.dtype).at[0].set(
                x_micro[0])
            outs0 = jnp.zeros((M,) + act_shape, x_a.dtype)
            sidx = jnp.arange(S)
            bcast = (S,) + (1,) * len(act_shape)

            def tick(carry, t):
                bufs, outs = carry
                bufs = constrain(bufs, "pipe", "data")
                # stage s works on microbatch t-s this tick: gather its
                # slice of every extra (mask etc.)
                midx = jnp.clip(t - sidx, 0, M - 1)
                ex_s = tuple(jnp.take(em, midx, axis=0) for em in ex_micro)
                ys = vstage(stacked, bufs, *ex_s)
                live = ((t - sidx) >= 0) & ((t - sidx) < M)
                ys = jnp.where(live.reshape(bcast), ys, 0)
                oidx = t - (S - 1)
                rec = jax.lax.dynamic_update_index_in_dim(
                    outs, ys[S - 1], jnp.clip(oidx, 0, M - 1), axis=0)
                outs = jnp.where(oidx >= 0, rec, outs)
                bufs = jnp.roll(ys, 1, axis=0)
                nxt = jax.lax.dynamic_index_in_dim(
                    x_micro, jnp.clip(t + 1, 0, M - 1), axis=0,
                    keepdims=False)
                inj = jnp.where(t + 1 < M, nxt, jnp.zeros_like(nxt))
                bufs = bufs.at[0].set(inj)
                return (bufs, outs), None

            (_, outs), _ = jax.lax.scan(
                tick, (bufs0, outs0), jnp.arange(M + S - 1))
            return outs.reshape((B,) + x_a.shape[1:])

        return pure(x, *param_leaves)


class PipelineStack(Layer):
    """Pipeline-parallel stack of identical shape-preserving blocks
    (transformer blocks): the Model-API surface for the 'pipe' mesh
    axis.

    Parameter paths are IDENTICAL to a plain block list (`self.blocks =
    PipelineStack([...])` exposes "blocks.0..." exactly like
    `self.blocks = [...]`), so checkpoints round-trip between pipelined
    and sequential instantiations, and DistOpt/ZeRO-1 compose
    unchanged.

    Forward dispatch:
      * a mesh with a 'pipe' axis (>1) in training → the GPipe schedule
        (_PipelineOp), n_micro microbatches over the batch dim;
      * otherwise (no mesh, eval, KV-cached decode, lazy init) → plain
        sequential application, numerically the reference behavior.

    Constraints: len(blocks) % stages == 0, all blocks structurally
    identical, batch % n_micro == 0, blocks buffer-free (same rule as
    layer.Remat); block-internal dropout draws one shared key (Llama
    blocks carry no dropout).
    """

    def __init__(self, blocks, stages: int, n_micro: Optional[int] = None,
                 remat: bool = False, name=None):
        super().__init__(name)
        if stages < 1 or len(blocks) % stages:
            raise ValueError(
                f"{len(blocks)} blocks do not divide into {stages} stages")
        self.inner = list(blocks)
        self.stages = stages
        self.n_micro = n_micro or stages
        self.remat = remat
        # remat must survive the sequential fallback too (a user who
        # sized HBM with remat=True would otherwise OOM on a pipe-less
        # mesh).  The wrappers share the inner blocks; bypass __setattr__
        # so they are not registered as duplicate sublayers for the
        # lazy-init walk.
        self.__dict__["_seq"] = ([Remat(b) for b in self.inner] if remat
                                 else self.inner)

    # param/state paths mirror a plain list attribute ("0.", "1.", ...)
    def get_params(self, prefix: str = "") -> Dict[str, Tensor]:
        out = dict()
        for i, blk in enumerate(self.inner):
            out.update(blk.get_params(f"{prefix}{i}."))
        return out

    def set_params(self, params, prefix: str = "") -> None:
        for i, blk in enumerate(self.inner):
            blk.set_params(params, f"{prefix}{i}.")

    def _get_buffers(self, prefix: str = "") -> Dict[str, Tensor]:
        out = dict()
        for i, blk in enumerate(self.inner):
            out.update(blk._get_buffers(f"{prefix}{i}."))
        return out

    def set_states(self, states, prefix: str = "") -> None:
        for i, blk in enumerate(self.inner):
            blk.set_states(states, f"{prefix}{i}.")

    def __iter__(self):
        return iter(self.inner)

    def __len__(self):
        return len(self.inner)

    def _pipe_live(self) -> bool:
        from .parallel import mesh as mesh_mod
        m = mesh_mod.current_mesh()
        if m is None:
            return False
        pipe = m.shape.get("pipe", 0)
        if pipe == self.stages > 1:
            return True
        if pipe > 1 and pipe != self.stages:
            # a misconfigured pipe axis must not silently train
            # unpipelined with pipe-axis devices replicating work
            import warnings
            warnings.warn(
                f"PipelineStack({self.name}): mesh 'pipe' axis is "
                f"{pipe} but stages={self.stages}; running "
                "sequentially (set pipeline_stages to the mesh's pipe "
                "size)", stacklevel=3)
        return False

    def forward(self, x: Tensor, *rest) -> Tensor:
        rest = tuple(r for r in rest if r is not None)

        def sequential():
            h = x
            for blk in self._seq:
                h = blk(h, *rest) if rest else blk(h)
            return h

        ready = all(b._initialized for b in self.inner)
        if not (ready and autograd.is_training() and self._pipe_live()):
            return sequential()
        why = self._pipe_blocker(x, rest)
        if why:
            import warnings
            warnings.warn(
                f"PipelineStack({self.name}) running sequentially: {why}",
                stacklevel=2)
            return sequential()
        leaves = []
        for blk in self.inner:
            leaves.extend(blk._param_list())
        extras = tuple(r.data if isinstance(r, Tensor) else jnp.asarray(r)
                       for r in rest)
        return _PipelineOp(self, extras)(x, *leaves)

    def _pipe_blocker(self, x, rest) -> Optional[str]:
        """Reason the GPipe path cannot run (None = it can)."""
        if any(b._buffer_list() for b in self.inner):
            return ("blocks hold non-trainable buffers (the pipelined "
                    "forward must be replayable)")
        B = x.shape[0]
        if B % self.n_micro:
            return f"batch {B} not divisible by n_micro={self.n_micro}"
        for r in rest:
            if isinstance(r, Tensor) and r.requires_grad:
                return "gradient-carrying extra args are unsupported"
            shape = getattr(r, "shape", None)
            if not shape or shape[0] != B:
                return (f"extra arg must be batch-leading (got shape "
                        f"{shape}, batch {B})")
        for blk in self.inner:
            for l in _walk_layers(blk):
                if isinstance(l, Dropout) and l.p > 0:
                    return ("Dropout(p>0) inside blocks would draw "
                            "different keys than sequential execution")
                if not getattr(type(l), "REMAT_SAFE", True):
                    return (f"{type(l).__name__} layers carry a "
                            "side-channel (e.g. MoE aux losses) the "
                            "schedule's pure replay would drop")
        return None


class Sequential(Layer):
    def __init__(self, *layers, name=None):
        super().__init__(name)
        self.layers = list(layers)

    def forward(self, x):
        for l in self.layers:
            x = l(x)
        return x


# loss layers (reference exposes these as layers as well as autograd fns)
class CrossEntropyLoss(Layer):
    def forward(self, logits, target):
        return autograd.softmax_cross_entropy(logits, target)


class MSELoss(Layer):
    def forward(self, x, t):
        return autograd.mse_loss(x, t)
