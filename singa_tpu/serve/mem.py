"""KV arena memory hierarchy (ISSUE 17): int8 KV blocks + a host-RAM
spill tier for cold prefix blocks.

Per-chip serve concurrency is bounded by HBM, and the paged arena's
unit of management — the block — is exactly the unit to compress and
to spill.  This module is the subsystem behind both tiers;
:class:`~singa_tpu.serve.slots.BlockPool` consumes it and
:class:`~singa_tpu.serve.engine.ServeEngine` exposes the knobs
(``kv_dtype=``, ``draft_kv_dtype=``, ``spill_blocks=``).

**Tier 1 — int8 KV blocks** (``kv_dtype="int8"``): the per-layer block
pools are :class:`~singa_tpu.ops.kv_cache.QuantKV` containers — int8
codes plus a per-position f32 absmax scale (a ``(block_size,)`` scale
vector per block, the EQuARX-style blockwise granule: one scale per
(K, D) slab a scatter writes).  Quantize-on-scatter and
dequantize-on-gather live INSIDE the existing gather/scatter
primitives, so an int8 engine compiles the same fixed program set
(prefill, decode, verify, handoff) with one jit entry each — the
``decode_int8`` hlocost flagship baseline commits the resulting
HBM-traffic drop.  Quantized KV breaks bitwise greedy identity BY
CONSTRUCTION, so the int8 tier is gated honestly through the
spec-verify referee: run the quantized arena as the draft/proposer
against a full-precision target referee and commit the measured accept
rate as the quality number (``bench.py --serve --arena-compare``).

**Tier 2 — host-RAM prefix spill** (``spill_blocks=N``): refcount-0
LRU prefix blocks — which already park in the pool's evictable list —
spill FULL-PRECISION (their exact device representation: int8 codes +
scales for a quantized arena, raw f32/bf16 otherwise) to host memory
instead of dying when the arena reclaims them.  On the next
prefix-cache hit the block is prefetched back into a free physical
block; JAX's async dispatch means the host never blocks on the copy —
the restore is enqueued and the prefill/decode programs queue behind
it.  A spilled-and-restored block round-trips BITWISE (device -> host
-> device of the same buffer), so the spill tier never changes a
stream: it only converts a re-prefill into a copy, which is the TTFT
win on re-hit.  Both seams (spill write, prefetch read) fire the
``serve.spill`` fault-injection site, and an injected fault degrades
to exactly the pre-spill behavior (the block dies / the prefix
re-prefills) — a performance loss, never a correctness one.
"""

from __future__ import annotations

from collections import OrderedDict
from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.kv_cache import QuantKV, dequantize_kv, quantize_kv

__all__ = ["QuantKV", "quantize_kv", "dequantize_kv", "KV_DTYPES",
           "normalize_kv_dtype", "quant_arena", "arena_block_bytes",
           "arena_bytes", "SpillStore", "read_block", "write_block",
           "write_blocks", "restore_compiled_count", "RESTORE_BATCH"]

#: accepted ``kv_dtype=`` spellings -> canonical form (None = the
#: model's native full-precision arena)
KV_DTYPES = {None: None, "f32": None, "full": None, "int8": "int8"}


def normalize_kv_dtype(kv_dtype) -> Optional[str]:
    """Canonicalize a ``kv_dtype=`` knob value (``None`` | ``"int8"``),
    rejecting typos loudly at construction."""
    if kv_dtype in KV_DTYPES:
        return KV_DTYPES[kv_dtype]
    raise ValueError(
        f"kv_dtype must be one of {sorted(k for k in KV_DTYPES if k)} "
        f"or None, got {kv_dtype!r}")


def quant_arena(model, num_blocks: int, block_size: int) -> List[Tuple]:
    """Per-layer ``(QuantKV, QuantKV)`` block pools shaped like
    ``model.init_caches(num_blocks, block_size)``.  ``eval_shape``
    keeps the full-precision arena abstract — construction never
    allocates a float copy, only the int8 codes + f32 scales."""
    spec = jax.eval_shape(lambda: model.init_caches(num_blocks,
                                                    block_size))
    out = []
    for ck, cv in spec:
        def pool(s):
            scale = s.shape[:2] + (1,) * (len(s.shape) - 2)
            return QuantKV(jnp.zeros(s.shape, jnp.int8),
                           jnp.zeros(scale, jnp.float32))
        out.append((pool(ck), pool(cv)))
    return out


def arena_block_bytes(caches, draft_caches=None) -> int:
    """Bytes ONE physical block occupies across every arena leaf —
    target + draft pools, int8 codes AND f32 scale tensors (QuantKV
    leaves flatten into both).  ``blocks_in_use * arena_block_bytes``
    is the honest HBM footprint the ``serve.blocks_in_use_bytes``
    gauge reports."""
    leaves = jax.tree.leaves(caches)
    if draft_caches is not None:
        leaves += jax.tree.leaves(draft_caches)
    return sum(int(np.prod(leaf.shape[1:])) * np.dtype(leaf.dtype).itemsize
               for leaf in leaves)


def arena_bytes(caches, draft_caches=None) -> int:
    """Total bytes of the block pools (every leaf, all blocks)."""
    leaves = jax.tree.leaves(caches)
    if draft_caches is not None:
        leaves += jax.tree.leaves(draft_caches)
    return sum(int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
               for leaf in leaves)


def read_block(caches, draft_caches, block: int) -> Dict[str, Any]:
    """Snapshot physical ``block``'s exact device representation (the
    spill payload): every leaf of the target (and draft) pools sliced
    at the block index, with the device->host copy STARTED but never
    awaited — an eviction must not put a sync barrier on the admission
    path that evicts.  The slices are fresh buffers, so the arena
    reclaiming the block cannot corrupt them; :class:`SpillStore`
    materializes the payload to host numpy off this path (see
    :meth:`SpillStore.put`), and the same-dtype round-trip through
    :func:`write_blocks` is bitwise."""
    def host(c):
        s = c[block]
        if hasattr(s, "copy_to_host_async"):
            s.copy_to_host_async()
        return s
    return {"kv": jax.tree.map(host, caches),
            "draft": (None if draft_caches is None
                      else jax.tree.map(host, draft_caches))}


#: spilled blocks restored per compiled-restore dispatch.  Restores are
#: padded up to this batch (by repeating the first block — duplicate
#: scatter indices carrying IDENTICAL updates, so the write is
#: deterministic) and chunked above it, keeping the restore program's
#: input shapes FIXED: it compiles once per arena structure and never
#: retraces, however many blocks an admission restores.
RESTORE_BATCH = 8


@partial(jax.jit, donate_argnums=(0,))
def _restore_step(arenas, idx, updates):
    """THE restore program: scatter ``RESTORE_BATCH`` spilled blocks
    into the arena pytree (target and draft together) in one donated
    dispatch — the arenas are updated in place, never copied.  A
    block-at-a-time eager restore pays per-leaf dispatch overhead that
    makes a re-hit LOSE to re-prefill on small models; one compiled
    scatter makes the spill tier's TTFT win real."""
    return jax.tree.map(lambda c, u: c.at[idx].set(u), arenas, updates)


def restore_compiled_count() -> int:
    """Jit-cache entry count of the restore program — the spill tier's
    own fixed-program invariant (at most one entry per arena
    structure; asserted alongside the engine's (1, 1) contract)."""
    return _restore_step._cache_size()


def write_blocks(caches, draft_caches, blocks: List[int],
                 payloads: List[Dict[str, Any]]):
    """Write :func:`read_block` payloads back into physical ``blocks``
    of (possibly different) pools — the prefetch restore, one
    :func:`_restore_step` dispatch per ``RESTORE_BATCH`` chunk.
    Returns ``(caches, draft_caches)``."""
    has_draft = (draft_caches is not None
                 and payloads[0]["draft"] is not None)
    for i in range(0, len(blocks), RESTORE_BATCH):
        bl = list(blocks[i:i + RESTORE_BATCH])
        pl = payloads[i:i + RESTORE_BATCH]
        pad = RESTORE_BATCH - len(bl)

        def stack(*hs):
            return np.stack(hs + hs[:1] * pad)
        idx = np.asarray(bl + bl[:1] * pad, np.int32)
        kv_u = jax.tree.map(stack, *[p["kv"] for p in pl])
        draft_u = (jax.tree.map(stack, *[p["draft"] for p in pl])
                   if has_draft else None)
        caches, new_draft = _restore_step(
            (caches, draft_caches if has_draft else None),
            idx, (kv_u, draft_u))
        if has_draft:
            draft_caches = new_draft
    return caches, draft_caches


def write_block(caches, draft_caches, block: int, payload: Dict[str, Any]):
    """Single-block :func:`write_blocks` (kept for tests and tools that
    round-trip one payload)."""
    return write_blocks(caches, draft_caches, [block], [payload])


class SpillStore:
    """Bounded host-RAM LRU of spilled prefix blocks, keyed by the
    pool's content-addressed chain keys.  Because a chain key commits
    to every token of the whole prefix (and block content is a
    deterministic function of those tokens under the shared weights),
    entries stay valid across arena rebuilds — recovery keeps the
    store, so a tenant's system prompt survives even an arena
    recovery.  Capacity overflow drops the OLDEST entry (those blocks
    simply re-prefill on their next hit, the pre-spill behavior)."""

    def __init__(self, max_blocks: int = 256):
        if max_blocks < 1:
            raise ValueError(
                f"spill capacity must be >= 1 block, got {max_blocks}")
        self.max_blocks = int(max_blocks)
        self._data: "OrderedDict[bytes, Dict[str, Any]]" = OrderedDict()
        #: keys whose payload still holds the device slices read_block
        #: snapshotted (D2H copy in flight, not yet numpy)
        self._lazy: set = set()
        #: entries dropped for capacity (cumulative)
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._data)

    def __contains__(self, key: bytes) -> bool:
        return key in self._data

    @property
    def bytes(self) -> int:
        """Host bytes currently held (payload arrays only)."""
        total = 0
        for payload in self._data.values():
            for part in (payload["kv"], payload["draft"]):
                if part is not None:
                    total += sum(a.nbytes for a in jax.tree.leaves(part))
        return total

    def _materialize(self, key: bytes) -> None:
        """Settle ``key``'s payload onto host numpy — called from
        :meth:`settle` (the engine's end-of-step, after its token sync,
        so the copies are already done and this is a collect, not a
        wait) and from :meth:`get`/:meth:`pop` before a payload is
        handed out."""
        if key not in self._lazy:
            return
        self._lazy.discard(key)
        def host(a):
            return np.asarray(a)  # singalint: disable=SGL008 the designed spill settle point: collects a D2H copy read_block started earlier, off the admission path
        p = self._data[key]
        self._data[key] = {
            "kv": jax.tree.map(host, p["kv"]),
            "draft": (None if p["draft"] is None
                      else jax.tree.map(host, p["draft"]))}

    def settle(self) -> None:
        """Materialize every pending payload to host numpy, releasing
        the device slice buffers.  The engine calls this at the end of
        each :meth:`~singa_tpu.serve.engine.ServeEngine.step` — right
        after the step's own token-extraction sync, when the spill
        copies have necessarily completed — so device-side spill
        buffers live at most one tick."""
        for key in list(self._lazy):
            self._materialize(key)

    def put(self, key: bytes, payload: Dict[str, Any]) -> None:
        self._data[key] = payload
        self._lazy.add(key)
        self._data.move_to_end(key)
        while len(self._data) > self.max_blocks:
            dropped, _ = self._data.popitem(last=False)
            self._lazy.discard(dropped)
            self.evictions += 1

    def get(self, key: bytes) -> Optional[Dict[str, Any]]:
        if key not in self._data:
            return None
        self._materialize(key)
        self._data.move_to_end(key)
        return self._data[key]

    def pop(self, key: bytes) -> Optional[Dict[str, Any]]:
        if key not in self._data:
            return None
        self._materialize(key)
        self._lazy.discard(key)
        return self._data.pop(key)
