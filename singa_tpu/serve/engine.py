"""ServeEngine — continuous-batching inference over a slot arena.

The engine turns the one-session decode loop of
``models/_generate.py`` into a multi-request server while keeping the
training stack's single-compiled-module discipline: for a given
(model, num_slots, max_len) it compiles exactly TWO XLA programs —

* **prefill-into-slot** — one request's prompt (padded to
  ``prefill_len``, true length passed as a traced scalar) runs the
  model's cached forward against a fresh cache row, which is then
  written into the arena at a traced slot index.  Variable prompt
  lengths therefore never change the compiled shape.
* **decode-over-slots** — ONE token for every slot per dispatch, with
  per-slot positions: RoPE offsets, cache scatters and attention
  limits are all (num_slots,) vectors inside the program (the ops
  layer grew per-row variants for exactly this), and inactive slots
  are masked — their position is clamped to 0 and their logits zeroed,
  so a half-empty arena still runs the same program.

Both programs thread params/buffers as jit arguments through the same
``_bound`` rebinding as generation, so weights are never baked into the
executables, and both donate the arena, so cache memory is updated in
place.  Submitting, admitting and evicting requests are host-side index
updates — no recompilation ever happens after warmup (asserted in
tests/test_serve.py via the jit cache size).

Greedy decode through the engine is token-identical to
``GenerateMixin.generate`` (same prefill/decode closures, same argmax),
which anchors the whole subsystem's correctness to existing behavior.

Resilience (ISSUE 4) — the engine survives its failure modes the way
``train.loop.TrainRunner`` survives training's, and every path below is
exercised by deterministic chaos tests (``singa_tpu.faults``,
tests/test_faults.py) rather than ad-hoc monkeypatching:

* **retry** — transient dispatch failures (RuntimeError/OSError before
  the program launches) are retried with bounded exponential backoff;
  the ``serve.prefill``/``serve.decode`` injection sites fire *before*
  the jitted call, so an injected fault leaves the donated arena intact
  and the retry re-dispatches the same tick.
* **quarantine** — a request whose prefill keeps failing is marked
  ``failed`` on its handle (with the error message) instead of crashing
  the engine; everyone else keeps decoding.
* **shedding** — deadline-aware overload control: queued requests whose
  deadline will expire before they could plausibly reach a slot are
  shed at the step boundary (reason ``shed``) instead of wasting a
  prefill.
* **recovery** — when decode dies past retries, or a Heartbeat detects
  a hang (``recover_on_hang=True``), the arena is rebuilt and every
  in-flight request is re-prefilled from prompt + tokens-so-far.
  Greedy decode makes the replay idempotent: recovered streams are
  bit-identical to an uninterrupted run.
* **drain/close** — ``drain()`` refuses new submissions while
  completing everything in the system; ``close()`` drains and releases
  the arena.

With ``heartbeat_timeout_s`` set and ``recover_on_hang`` unset, a hung
dispatch still surfaces as a clean abort instead of wedging the server.
Quarantines and recoveries land as durable ``incident`` records
(``record_store``), linted by ``tools/record_check.py``.
"""

from __future__ import annotations

import itertools
import threading
import time
import warnings
from contextlib import nullcontext
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import faults
from ..models._generate import _bound, decode_step, prefill_step
from ..obs import events
from ..obs import record as obs_record
from ..utils import failure
from ..utils.failure import Heartbeat
from .metrics import ServeMetrics
from .scheduler import (EVICTED, FAILED, FINISHED, RUNNING, QueueFull,
                        Request, RequestHandle, Scheduler)
from .slots import SlotPool

__all__ = ["ServeEngine", "QueueFull", "EngineClosed"]

#: distinguishes engines built in the same second+pid (run_id suffix)
_ENGINE_SEQ = itertools.count()


class EngineClosed(RuntimeError):
    """submit()/step() refused: the engine is draining or closed."""


class ServeEngine:
    """Continuous-batching engine over one decoder model.

        eng = ServeEngine(model, num_slots=8, max_len=256)
        h = eng.submit(prompt_ids, max_new_tokens=64, deadline_s=30.0)
        eng.run_until_idle()
        full = h.result()              # prompt + generated tokens

    ``step()`` advances the whole arena by one decode tick (evict →
    admit/prefill → decode), delivering one token to every live request
    and invoking their streaming ``on_token`` callbacks.

    Decoding is greedy — the serving counterpart of
    ``generate(temperature=0)`` and token-identical to it.
    """

    def __init__(self, model, num_slots: int, max_len: int, *,
                 prefill_len: Optional[int] = None,
                 max_queue: Optional[int] = None,
                 param_dtype=None,
                 heartbeat_timeout_s: Optional[float] = None,
                 on_failure=None,
                 max_dispatch_retries: int = 2,
                 backoff_base: float = 0.05,
                 backoff_max: float = 1.0,
                 recover_on_hang: bool = False,
                 max_recoveries: int = 2,
                 record_store: Optional[str] = None,
                 run_id: Optional[str] = None,
                 _sleep: Callable[[float], None] = time.sleep):
        self.model = model
        self.prefill_len = int(prefill_len or max_len - 1)
        if not 0 < self.prefill_len < max_len:
            raise ValueError(
                f"prefill_len must be in (0, max_len), got "
                f"{self.prefill_len} for max_len {max_len}")
        max_pos = getattr(getattr(model, "cfg", None), "max_position", None)
        if max_pos is not None and max_len > max_pos:
            raise ValueError(
                f"max_len ({max_len}) exceeds the model's max_position "
                f"({max_pos})")
        self.sched = Scheduler(
            max_queue=2 * num_slots if max_queue is None else max_queue)
        self.metrics = ServeMetrics()
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self._on_failure = on_failure
        self.max_dispatch_retries = int(max_dispatch_retries)
        if self.max_dispatch_retries < 0:
            raise ValueError(f"max_dispatch_retries must be >= 0, got "
                             f"{max_dispatch_retries}")
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self.recover_on_hang = bool(recover_on_hang)
        self.max_recoveries = int(max_recoveries)
        self.record_store = record_store
        self.run_id = run_id or \
            f"{obs_record.new_run_id('serve')}-e{next(_ENGINE_SEQ)}"
        self._sleep = _sleep
        self._draining = False
        self._closed = False
        # set by the Heartbeat monitor thread, consumed at the next
        # step boundary by the step thread (which owns the arena)
        self._recover_flag = threading.Event()
        self._recoveries = 0
        self._incident_seq = itertools.count()
        self._tick_ewma: Optional[float] = None   # measured step() wall s

        # weights snapshotted once (same pattern as _gen_setup); decode
        # is weight-read bound, so an optional one-time bf16 cast halves
        # per-token HBM traffic on TPU
        params = {n: t.data for n, t in model.get_params().items()}
        if not params:
            raise ValueError(
                "model has no initialized params — call model.compile() "
                "(or run one forward) before building a ServeEngine")
        buffers = {n: t.data for n, t in model._get_buffers().items()}
        arena_dtype = None
        if param_dtype is not None:
            params = {n: (a.astype(param_dtype)
                          if jnp.issubdtype(a.dtype, jnp.floating) else a)
                      for n, a in params.items()}
            # the arena must match the dtype init_caches picks under the
            # CAST params inside the prefill trace (models size their
            # caches off the bound weights' dtype) — otherwise the
            # fresh-row splice type-mismatches at trace time.  eval_shape
            # under the cast binding reads that dtype without allocating.
            with _bound(model, params, buffers):
                spec = jax.eval_shape(lambda: model.init_caches(1, 2))
            arena_dtype = jax.tree.leaves(spec)[0].dtype
        self._params, self._buffers = params, buffers
        # arena construction args kept for recovery rebuilds
        self._num_slots, self._max_len = num_slots, max_len
        self._arena_dtype = arena_dtype
        self.pool = SlotPool(model, num_slots, max_len, dtype=arena_dtype)

        self._running: Dict[int, Request] = {}      # slot -> request
        # device-resident per-slot last tokens: written by prefill (the
        # request's first token) and decode (each next token); the host
        # only ever FETCHES this small int vector — tokens are never
        # uploaded, so the decode hot loop is one dispatch + one tiny
        # fetch per tick
        self._toks = jnp.zeros((num_slots,), jnp.int32)

        # ---- the exactly-two compiled programs --------------------------
        pf = prefill_step(model, max_len, last_only=False)

        def prefill_into_slot(params, buffers, ids, length, slot, toks,
                              caches):
            logits, fresh = pf(params, buffers, ids)
            last = jax.lax.dynamic_slice_in_dim(
                logits, length - 1, 1, axis=1)[:, 0, :]
            # greedy pick in-program (jnp.argmax — bit-identical to
            # _pick_impl's temperature-0 branch in generate())
            tok = jnp.argmax(last, axis=-1).astype(jnp.int32)[0]
            toks = toks.at[slot].set(tok)
            new = [
                (jax.lax.dynamic_update_slice_in_dim(ak, fk, slot, axis=0),
                 jax.lax.dynamic_update_slice_in_dim(av, fv, slot, axis=0))
                for (ak, av), (fk, fv) in zip(caches, fresh)]
            return toks, new

        dec = decode_step(model)

        def decode_over_slots(params, buffers, toks, pos, active, caches):
            # inactive slots are masked: position clamped to 0 (their
            # stale cache row is overwritten wholesale by the next
            # prefill, so the position-0 scribble is harmless and keeps
            # every row's attention window non-empty → no NaN softmax),
            # and their token entry frozen so nothing downstream reads a
            # garbage argmax
            posc = jnp.where(active, pos, 0)
            logits, caches = dec(params, buffers, toks[:, None], posc,
                                 caches)
            picked = jnp.argmax(logits.astype(jnp.float32),
                                axis=-1).astype(jnp.int32)
            new_toks = jnp.where(active, picked, toks)
            new_pos = jnp.where(active, pos + 1, pos)
            return new_toks, new_pos, caches

        self._prefill = jax.jit(prefill_into_slot, donate_argnums=(6,))
        self._decode = jax.jit(decode_over_slots, donate_argnums=(5,))

    # -- introspection ----------------------------------------------------
    def compiled_counts(self):
        """(prefill, decode) jit-cache entry counts — the no-recompile
        invariant says both stay at 1 after warmup (tested)."""
        return (self._prefill._cache_size(), self._decode._cache_size())

    @property
    def pending(self) -> int:
        """Requests still in flight (queued + running)."""
        return self.sched.depth + len(self._running)

    # -- submission --------------------------------------------------------
    def submit(self, prompt_ids, *, max_new_tokens: int,
               deadline_s: Optional[float] = None,
               eos_id: Optional[int] = None,
               on_token=None) -> RequestHandle:
        """Queue one generation request; returns its handle.

        Raises :class:`QueueFull` when admission control refuses the
        request — the wait queue is at capacity.  Admission out of the
        queue into slots happens only at ``step()`` boundaries, so a
        burst of more than ``max_queue`` un-stepped submissions is
        rejected even while slots are free (size ``max_queue`` for the
        largest burst to absorb; default ``2 * num_slots``).  Raises
        ``ValueError`` when the request cannot ever fit the arena
        (prompt longer than ``prefill_len``, or prompt + budget past
        ``max_len`` — the arena guarantee that decode never writes out
        of bounds is enforced here, at the door).  Raises
        :class:`EngineClosed` while draining or after ``close()``."""
        if self._closed:
            raise EngineClosed("submit() on a closed engine")
        if self._draining:
            raise EngineClosed(
                "engine is draining — new submissions are refused while "
                "in-flight requests complete")
        req = Request(prompt_ids, max_new_tokens, deadline_s, eos_id,
                      on_token)
        p = req.prompt.size
        if p > self.prefill_len:
            raise ValueError(
                f"prompt ({p} tokens) exceeds prefill_len "
                f"({self.prefill_len})")
        if p + req.max_new_tokens > self.pool.max_len:
            raise ValueError(
                f"prompt ({p}) + max_new_tokens ({req.max_new_tokens}) "
                f"= {p + req.max_new_tokens} exceeds max_len "
                f"({self.pool.max_len})")
        try:
            self.sched.offer(req)
        except QueueFull:
            self.metrics.on_reject()
            raise
        self.metrics.on_submit()
        return req.handle

    # -- the engine loop ---------------------------------------------------
    def step(self) -> int:
        """One continuous-batching tick: recovery (if requested by the
        hang watchdog) → deadline eviction → overload shedding →
        admission (prefill queued requests into free slots) → one decode
        over all active slots.  Returns the number of tokens
        delivered."""
        if self._closed:
            raise EngineClosed("step() on a closed engine")
        with events.span("serve.step"):
            now = time.monotonic()
            delivered = 0

            # 0. hang recovery — the Heartbeat monitor thread can only
            #    REQUEST it; the rebuild must run here, on the step
            #    thread, which owns the arena
            if self._recover_flag.is_set():
                self._recover_flag.clear()
                self._recover("heartbeat")

            # 1. deadline eviction — queued requests that died waiting
            #    and running requests past their deadline vacate first,
            #    so their slots are admittable this same tick
            for req in self.sched.expire_queued(now):
                self.metrics.on_evict("deadline")
            for slot in [s for s, r in self._running.items()
                         if r.expired(now)]:
                req = self._running[slot]
                req.finish_reason = "deadline"
                self._finalize(slot, evicted=True)

            # 1b. deadline-aware overload shedding — queued requests
            #     that cannot plausibly deliver a first token before
            #     their deadline are shed before burning a prefill
            for req in self.sched.shed_overload(now, self._eta_first_token):
                self.metrics.on_evict("shed")

            # 2. admission — prefill into free slots between decode steps
            while self.pool.free_count:
                req = self.sched.pop_for_admission()
                if req is None:
                    break
                delivered += self._admit(req)

            # 3. one decode tick over the whole arena; a decode that
            #    died past its retry budget escalates to an arena
            #    rebuild + re-prefill instead of crashing the engine
            if self._running:
                try:
                    delivered += self._decode_tick()
                except (RuntimeError, OSError) as e:
                    if isinstance(e, failure.FailureDetected):
                        raise
                    self._recover(f"decode: {type(e).__name__}: {e}")

            self.metrics.on_step(self.sched.depth, self.pool.active_count)
            dt = time.monotonic() - now
            self._tick_ewma = dt if self._tick_ewma is None else \
                0.8 * self._tick_ewma + 0.2 * dt
        return delivered

    def _eta_first_token(self, position: int) -> float:
        """Seconds until the queued request at ``position`` could
        plausibly deliver its first token.  Shedding runs immediately
        before admission in the same tick, so the first
        ``pool.free_count`` queued requests prefill THIS tick — eta 0.0,
        never shed (a truly-expired deadline is eviction's job, not
        shedding's).  Requests behind that window wait about one
        measured tick per admission wave of ``num_slots``.  0.0 before
        any tick has been measured — shedding never fires without
        timing evidence."""
        if self._tick_ewma is None:
            return 0.0
        free = self.pool.free_count
        if position < free:
            return 0.0
        return self._tick_ewma * (1 + (position - free)
                                  // self.pool.num_slots)

    def run_until_idle(self, max_steps: Optional[int] = None) -> None:
        """Drive ``step()`` until no request is queued or running.  With
        ``heartbeat_timeout_s`` set, a Heartbeat watchdog guards every
        tick — a hung decode (dead device, wedged tunnel) aborts cleanly
        instead of wedging the server, or, with ``recover_on_hang``,
        requests an arena rebuild + re-prefill at the next step
        boundary."""
        hb = Heartbeat(timeout=self.heartbeat_timeout_s,
                       on_failure=(self._hb_failure if self.recover_on_hang
                                   else self._on_failure)) \
            if self.heartbeat_timeout_s else None
        n = 0
        with hb if hb is not None else nullcontext():
            while self.pending:
                self.step()
                n += 1
                if hb is not None:
                    hb.beat(n)
                    if hb.fired and self.recover_on_hang:
                        # the monitor thread exits after firing once;
                        # re-arm it so a later hang in this same drive
                        # is also caught
                        hb.stop()
                        hb.start()
                if max_steps is not None and n >= max_steps:
                    break
        if not self.pending:
            # a fully drained system is proof the last recovery took —
            # give future incidents a fresh rebuild budget, and drop any
            # rebuild REQUEST a hang on the final tick left behind (the
            # late decode still delivered everything; rebuilding a
            # healthy idle arena at the next drive's first step would
            # burn recovery budget and record a bogus incident)
            self._recoveries = 0
            self._recover_flag.clear()

    def drain(self, max_steps: Optional[int] = None) -> None:
        """Stop accepting new requests and complete everything already
        in the system: queued requests still get admitted, in-flight
        slots decode to completion (or eviction).  ``submit()`` raises
        :class:`EngineClosed` from the moment drain begins — draining is
        one-way, the step before :meth:`close`.  Safe to call
        repeatedly."""
        self._draining = True
        self.run_until_idle(max_steps=max_steps)

    def close(self) -> None:
        """``drain()`` to idle, then release the engine: the arena and
        token buffer are dropped (freeing device memory) and every
        subsequent ``submit()``/``step()`` raises :class:`EngineClosed`.
        Idempotent."""
        if self._closed:
            return
        self.drain()
        self._closed = True
        self.pool = None
        self._toks = None

    def __enter__(self) -> "ServeEngine":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    # -- internals ---------------------------------------------------------
    def _dispatch(self, site: str, fn, args, **attrs):
        """One guarded jitted dispatch: the injection site fires first
        (host-side, BEFORE the call — the donated arena is still
        intact), and transient RuntimeError/OSError is retried with
        bounded exponential backoff.  Retry scope mirrors
        ``train.loop``: sound for dispatch-level transients (tunnel
        hiccup before launch, injected faults); a REAL mid-execution
        failure invalidates the donated arena, so retries fail too and
        the error escalates to the caller — quarantine for prefill,
        arena recovery for decode."""
        attempt = 0
        while True:
            try:
                faults.fire(site, attempt=attempt, **attrs)
                return fn(*args)
            except (RuntimeError, OSError) as e:
                if isinstance(e, failure.FailureDetected):
                    raise
                if attempt >= self.max_dispatch_retries:
                    raise
                delay = min(self.backoff_max,
                            self.backoff_base * (2 ** attempt))
                attempt += 1
                self.metrics.on_retry(site)
                self._sleep(delay)

    def _admit(self, req: Request) -> int:
        slot = self.pool.alloc()
        assert slot is not None, "admission with no free slot"
        # replay_ids == prompt for a fresh request; for a request
        # re-admitted by arena recovery it is prompt + tokens-so-far,
        # whose greedy prefill pick IS the next decode token — the
        # recovery re-prefill is idempotent
        replay = req.replay_ids()
        P = replay.size
        ids = np.zeros((1, self.prefill_len), np.int32)
        ids[0, :P] = replay
        first = not req.tokens
        try:
            with events.span("serve.prefill", slot=slot, prompt=P):
                self._toks, self.pool.caches = self._dispatch(
                    "serve.prefill", self._prefill,
                    (self._params, self._buffers, jnp.asarray(ids),
                     jnp.asarray(P, jnp.int32),
                     jnp.asarray(slot, jnp.int32),
                     self._toks, self.pool.caches),
                    rid=req.rid)
                tok = int(np.asarray(self._toks)[slot])
        except (RuntimeError, OSError) as e:
            if isinstance(e, failure.FailureDetected):
                raise
            # the injected/transient failure fired before dispatch, so
            # the slot row was never touched — hand it back and fail
            # only THIS request, not the engine
            self.pool.release(slot)
            self._quarantine(req, e)
            return 0
        self.pool.activate(slot, P)
        req.slot = slot
        req.state = RUNNING
        self._running[slot] = req
        if first:
            # recovery re-prefills count under serve.recoveries, not
            # here — ``admitted`` stays comparable to ``submitted``
            self.metrics.on_admit()
        done = req.deliver(tok)       # prefill yields the (next) token
        if first:
            self.metrics.on_first_token(req.ttft_s)
        if req.on_token is not None:
            req.on_token(tok, req.handle)
        if done:
            self._finalize(slot)
        return 1

    def _quarantine(self, req: Request, err: Exception) -> None:
        """Repeatedly-poisoned prefill: surface a per-request failure
        status (handle.failed / handle.error), never an engine crash."""
        req.state = FAILED
        req.finish_reason = "quarantined"
        req.error = (f"prefill failed after "
                     f"{self.max_dispatch_retries + 1} attempt(s): "
                     f"{type(err).__name__}: {err}")
        self.metrics.on_quarantine()
        self._incident("serve.prefill", type(err).__name__,
                       f"req:{req.rid}", "quarantined",
                       self.max_dispatch_retries + 1)
        warnings.warn(f"serve: request {req.rid} quarantined: "
                      f"{req.error}", stacklevel=2)

    def _decode_tick(self) -> int:
        t0 = time.perf_counter()
        with events.span("serve.decode", active=len(self._running)):
            self._toks, new_pos, self.pool.caches = self._dispatch(
                "serve.decode", self._decode,
                (self._params, self._buffers, self._toks,
                 self.pool.pos, self.pool.active, self.pool.caches),
                active=len(self._running))
            toks = np.asarray(self._toks)    # tiny fetch: num_slots ints
        self.pool.pos = new_pos
        dt = time.perf_counter() - t0
        delivered = 0
        for slot in list(self._running):
            req = self._running[slot]
            tok = int(toks[slot])
            done = req.deliver(tok)
            self.metrics.on_token(dt)
            if req.on_token is not None:
                req.on_token(tok, req.handle)
            delivered += 1
            if done:
                self._finalize(slot)
        return delivered

    def _finalize(self, slot: int, evicted: bool = False) -> None:
        req = self._running.pop(slot)
        self.pool.release(slot)
        req.state = EVICTED if evicted else FINISHED
        self.metrics.on_evict(req.finish_reason or "unknown")

    # -- recovery ----------------------------------------------------------
    def recover(self, reason: str = "requested") -> None:
        """Rebuild the arena and re-prefill every in-flight request —
        the path behind Heartbeat hang detection, also callable directly
        after an external device event.  Each running request is
        requeued at the HEAD of the queue and re-prefilled from
        ``prompt + tokens-so-far``; greedy decode makes that replay
        idempotent, so however many times recovery runs, the final
        streams are bit-identical to an uninterrupted run.  A request
        whose replay no longer fits ``prefill_len`` is failed
        (``unrecoverable``) rather than silently truncated."""
        self._recover(reason)

    def _recover(self, reason: str) -> None:
        self._recoveries += 1
        if self._recoveries > self.max_recoveries:
            raise RuntimeError(
                f"serve engine exceeded max_recoveries="
                f"{self.max_recoveries} (last reason: {reason}) — the "
                f"fault is not transient; surfacing it instead of "
                f"rebuilding forever")
        with events.span("serve.recover", reason=reason):
            inflight = sorted(self._running.values(), key=lambda r: r.rid)
            self._running.clear()
            # fresh arena + token buffer: same shapes/dtypes, so the two
            # compiled programs are reused — recovery never recompiles
            self.pool = SlotPool(self.model, self._num_slots,
                                 self._max_len, dtype=self._arena_dtype)
            self._toks = jnp.zeros((self._num_slots,), jnp.int32)
            requeue = []
            for req in inflight:
                if req.replay_ids().size > self.prefill_len:
                    req.state = FAILED
                    req.finish_reason = "unrecoverable"
                    req.error = (
                        f"cannot re-prefill after arena rebuild: prompt "
                        f"+ generated = {req.replay_ids().size} tokens "
                        f"exceeds prefill_len ({self.prefill_len})")
                    self.metrics.on_evict("unrecoverable")
                    self._incident("serve.arena", reason,
                                   f"req:{req.rid}", "unrecoverable", 0)
                else:
                    requeue.append(req)
            self.sched.requeue_front(requeue)
            self.metrics.on_recover(len(requeue))
            self._incident("serve.arena", reason,
                           f"inflight:{len(requeue)}", "recovered",
                           self._recoveries)

    def _hb_failure(self, age: float, last_beat: int) -> None:
        """Heartbeat monitor-thread path (``recover_on_hang``): only
        REQUEST recovery — the step thread owns the arena and performs
        the rebuild at its next step boundary (a hung dispatch cannot be
        preempted from here anyway; an injected hang simply returns
        late).  A user ``on_failure`` still gets the observation."""
        events.counter("serve.hangs", 1, age_s=round(age, 3))
        self._recover_flag.set()
        if self._on_failure is not None:
            self._on_failure(age, last_beat)

    # -- durable incident records -----------------------------------------
    def _incident(self, site: str, fault: str, ref, outcome: str,
                  retries: int) -> None:
        """Append one ``incident`` entry to the run-record store (when
        ``record_store`` is set).  Best-effort: the record is evidence,
        not a dependency — a full disk must not turn a survived fault
        into a crash."""
        events.counter("serve.incident", 1, site=site, outcome=outcome)
        if not self.record_store:
            return
        try:
            platform = jax.default_backend()
            dev = jax.devices()[0]
            payload = {"site": site, "fault": fault, "ref": ref,
                       "outcome": outcome, "retries": int(retries),
                       "engine_run": self.run_id}
            entry = obs_record.new_entry(
                "incident", platform, platform != "tpu",
                getattr(dev, "device_kind", "") or platform,
                run_id=f"{self.run_id}-inc{next(self._incident_seq)}",
                payload=payload)
            obs_record.RunRecord(self.record_store).append(entry)
        except Exception as e:
            warnings.warn(f"could not append incident record: "
                          f"{type(e).__name__}: {e}", stacklevel=2)
