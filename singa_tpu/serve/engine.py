"""ServeEngine — continuous-batching inference over a paged KV arena.

The engine turns the one-session decode loop of
``models/_generate.py`` into a multi-request server while keeping the
training stack's single-compiled-module discipline: for a given
(model, num_slots, max_len, block_size) it compiles exactly TWO XLA
programs —

* **prefill-chunk** — ``block_size`` tokens of one request's prompt at
  a traced block-aligned offset: the slot's block-table row is
  gathered into a dense cache view, the chunk's k/v are written at
  [pos, pos+block_size) and exactly ONE physical block is scattered
  back (``ops.kv_cache.scatter_block_kv``).  A prompt prefills as
  ``ceil(len / block_size)`` dispatches of this one program — and a
  request whose leading prompt blocks are already resident (prefix
  cache) SKIPS those dispatches entirely: prefill cost scales with the
  unshared suffix, which is the TTFT win paging buys.
* **decode-over-block-tables** — ONE token for every slot per
  dispatch: the (num_slots, max_blocks) block tables gather every
  slot's dense view, per-slot positions drive RoPE offsets and
  attention limits as (num_slots,) vectors, and each slot's new k/v is
  scattered to ``[table[slot, pos // bs], pos % bs]``
  (``scatter_token_kv``).  Inactive slots are masked — position
  clamped to 0, writes redirected to the null block, token entries
  frozen — so a half-empty arena still runs the same program.

Both programs thread params/buffers as jit arguments through the same
``_bound`` rebinding as generation, so weights are never baked into the
executables, and both donate the arena, so cache memory is updated in
place.  Submitting, admitting, growing and evicting requests are
host-side index updates — no recompilation ever happens after warmup
(asserted in tests/test_serve.py via the jit cache size).

Greedy decode through the engine is token-identical to
``GenerateMixin.generate`` (same cached forward, same argmax), which
anchors the whole subsystem's correctness to existing behavior.

Admission counts FREE BLOCKS, not slots: a request needs a table row
AND enough blocks for its prompt (minus the shared prefix), and decode
grows a slot by one block when its position crosses a block boundary.
When growth finds no free or evictable block, the youngest running
request is PREEMPTED — its blocks are released and it re-queues at the
head, to be re-prefilled later from prompt + tokens-so-far (greedy
decode makes the replay idempotent, so preemption never changes a
stream).

Resilience (ISSUE 4, extended to the paged arena) — every path below
is exercised by deterministic chaos tests (``singa_tpu.faults``,
tests/test_faults.py):

* **retry** — transient dispatch failures (RuntimeError/OSError before
  the program launches) are retried with bounded exponential backoff;
  the ``serve.prefill``/``serve.decode`` injection sites fire *before*
  the jitted call, so an injected fault leaves the donated arena intact
  and the retry re-dispatches the same tick.
* **quarantine** — a request whose prefill (or admission-time block
  allocation, site ``serve.block_alloc``) keeps failing is marked
  ``failed`` on its handle instead of crashing the engine.
* **shedding** — deadline-aware overload control: queued requests whose
  deadline will expire before they could plausibly reach a slot are
  shed at the step boundary (reason ``shed``) instead of wasting a
  prefill.
* **recovery** — when decode or a decode-time block allocation dies
  past retries, or a Heartbeat detects a hang (``recover_on_hang``),
  the arena is rebuilt — fresh block pool, fresh tables, fresh
  refcounts, empty prefix cache — and every in-flight request is
  re-prefilled from prompt + tokens-so-far.  Greedy decode makes the
  replay idempotent: recovered streams are bit-identical to an
  uninterrupted run.
* **drain/close** — ``drain()`` refuses new submissions while
  completing everything in the system; ``close()`` drains and releases
  the arena.

With ``heartbeat_timeout_s`` set and ``recover_on_hang`` unset, a hung
dispatch still surfaces as a clean abort instead of wedging the server.
Quarantines and recoveries land as durable ``incident`` records
(``record_store``), linted by ``tools/record_check.py``.

Observability (ISSUE 11): every request gets a trace id
(``handle.trace_id``) activated around its admission, prefill chunks,
token deliveries and eviction, so the whole request reconstructs as one
trace in the obs event stream (``tools/obsq.py trace``) — TTFT and
tokens/s are derivable from it and asserted equal to the histogram
metrics.  The engine also keeps a :class:`~singa_tpu.obs.flight.
FlightRecorder` ring of its recent events (in-memory, sink or no sink);
each quarantine/recovery dumps the ring to
``<record dir>/incidents/<ts>-<site>.jsonl`` and the incident record's
``flight_ref`` points at it.  With no ``record_store`` and no sink the
engine performs zero file writes.

Speculative decoding (ISSUE 13): ``ServeEngine(draft_model=, spec_k=)``
replaces the per-tick decode with a **verify-k round** — the THIRD
gated program (serve/spec.py): the draft proposes k tokens per slot
(its KV blocks ride the same block tables, a parallel pool in
``BlockPool``), the target scores all k+1 window positions in one
dispatch, the longest matching greedy prefix commits and rejected
positions roll back by truncating the slot's position/limit.  The
delivered tokens are the target's own picks, so speculative greedy
streams are bitwise identical to ``generate()`` by construction; an
injected/transient verify failure past retries falls back to a plain
decode tick (site ``serve.verify``).  The fixed compiled set becomes
(prefill, decode, verify, handoff), asserted via
:meth:`spec_compiled_counts`.

Disaggregated serving (ISSUE 12): the engine is also the worker unit
of :mod:`singa_tpu.serve.disagg` — a prefill pool ticks with
``step(decode=False)`` and hands finished prefills to a decode pool
through :meth:`extract_handoff`/:meth:`inject_handoff` (KV blocks move
via the optional third compiled program, a fixed-shape
``handoff_gather``; refcounts and prefix-cache keys transfer with the
blocks).  Same-config workers share one set of executables via
``programs=`` (:class:`SharedPrograms`), so a whole tier costs one
engine's compiles.
"""

from __future__ import annotations

import itertools
import threading
import time
import warnings
from contextlib import nullcontext
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import faults
from ..models._generate import _bound, decode_step, resume_step
from ..obs import attr as obs_attr
from ..obs import events
from ..obs import flight as obs_flight
from ..obs import record as obs_record
from ..obs import trace as obs_trace
from ..ops import kv_cache as kv_ops
from ..utils import failure
from ..utils.failure import Heartbeat
from . import mem as serve_mem
from .metrics import ServeMetrics
from .scheduler import (EVICTED, FAILED, FINISHED, QUEUED, RUNNING,
                        QueueFull, Request, RequestHandle, Scheduler,
                        eta_first_token)
from .slots import BlockPool

__all__ = ["ServeEngine", "QueueFull", "EngineClosed", "SharedPrograms"]

#: distinguishes engines built in the same second+pid (run_id suffix)
_ENGINE_SEQ = itertools.count()


class EngineClosed(RuntimeError):
    """submit()/step() refused: the engine is draining or closed."""


class SharedPrograms(NamedTuple):
    """The compiled-program bundle one engine can lend to another
    (``ServeEngine(..., programs=template.programs())``) — how a
    disaggregated worker pool keeps the whole tier on ONE set of
    executables: every same-config worker dispatches through the same
    jitted callables, so N prefill + M decode workers cost exactly the
    template's compiles (the per-worker jit-cache assertions then count
    the shared caches).  Sharing requires the SAME model object and
    block size (the closures capture both); arena shapes
    (num_slots/max_len/num_blocks) may differ, but each distinct shape
    adds a cache entry to the shared programs, so homogeneous pools are
    what keeps the per-worker (1, 1) invariant literal."""

    model_ref: object
    block_size: int
    prefill: object
    decode: object
    handoff: object
    #: speculative decoding (serve/spec.py): the draft model the verify
    #: program's closures capture (None for a plain engine), the
    #: trace-time k baked into that program, and the verify executable
    #: itself.  Sharing requires the SAME draft object and equal k —
    #: a tier mixes spec and plain engines only by NOT sharing programs.
    draft_ref: object = None
    spec_k: int = 0
    verify: object = None
    #: KV memory hierarchy (ISSUE 17, serve/mem.py): the arena storage
    #: formats the closures were TRACED against (None = full precision,
    #: "int8" = QuantKV codes + scales).  A format mismatch would not
    #: error — it would silently add a second jit-cache entry per
    #: program and break the (1, 1) invariant — so sharing validates
    #: equality up front.
    kv_dtype: object = None
    draft_kv_dtype: object = None


class ServeEngine:
    """Continuous-batching engine over one decoder model.

        eng = ServeEngine(model, num_slots=8, max_len=256, block_size=32)
        h = eng.submit(prompt_ids, max_new_tokens=64, deadline_s=30.0)
        eng.run_until_idle()
        full = h.result()              # prompt + generated tokens

    ``step()`` advances the whole arena by one decode tick (evict →
    admit/prefill → decode), delivering one token to every live request
    and invoking their streaming ``on_token`` callbacks.

    ``num_blocks`` sizes the physical block pool (default: capacity
    parity with a fixed ``(num_slots, max_len)`` arena); a SMALLER pool
    with MORE slots is how paging admits more concurrent requests in
    the same memory.  ``share_prefix=False`` disables prefix-cache
    sharing (every prompt block is private).

    Decoding is greedy — the serving counterpart of
    ``generate(temperature=0)`` and token-identical to it.
    """

    def __init__(self, model, num_slots: int, max_len: int, *,
                 block_size: int = 16,
                 num_blocks: Optional[int] = None,
                 share_prefix: bool = True,
                 max_queue: Optional[int] = None,
                 param_dtype=None,
                 heartbeat_timeout_s: Optional[float] = None,
                 on_failure=None,
                 max_dispatch_retries: int = 2,
                 backoff_base: float = 0.05,
                 backoff_max: float = 1.0,
                 recover_on_hang: bool = False,
                 max_recoveries: int = 2,
                 record_store: Optional[str] = None,
                 run_id: Optional[str] = None,
                 programs: Optional[SharedPrograms] = None,
                 draft_model=None, spec_k: Optional[int] = None,
                 kv_dtype=None, draft_kv_dtype=None,
                 spill_blocks: Optional[int] = None,
                 _sleep: Callable[[float], None] = time.sleep):
        self.model = model
        # speculative decoding (serve/spec.py): a draft model turns the
        # per-tick decode into a verify-k round — k proposals + the
        # pending token scored by ONE target dispatch.  spec_k=None
        # with a draft resolves the window depth from the committed
        # best-config table (ISSUE 14 / ROADMAP item 2b: the table's k
        # comes from measured accept_rate / tokens_per_dispatch
        # records); an explicit integer always wins
        if draft_model is not None and spec_k is None:
            from ..autotune import table as autotune_table
            spec_k = autotune_table.resolve_spec_k(model)
        if spec_k is None:
            spec_k = 0
        if (draft_model is None) != (spec_k == 0):
            raise ValueError(
                "speculative decoding needs BOTH draft_model and "
                f"spec_k >= 1 (got draft_model="
                f"{'set' if draft_model is not None else 'None'}, "
                f"spec_k={spec_k})")
        if spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {spec_k}")
        self.draft_model = draft_model
        self.spec_k = int(spec_k)
        max_pos = getattr(getattr(model, "cfg", None), "max_position", None)
        if max_pos is not None and max_len > max_pos:
            raise ValueError(
                f"max_len ({max_len}) exceeds the model's max_position "
                f"({max_pos})")
        self.share_prefix = bool(share_prefix)
        self.sched = Scheduler(
            max_queue=2 * num_slots if max_queue is None else max_queue)
        # the incident flight ring (ISSUE 11): always recording (bounded
        # in-memory, zero file I/O), registered for fault-fire
        # broadcasts; dumps happen only when record_store names a place
        # for the incident evidence to live
        self.flight = obs_flight.register(obs_flight.FlightRecorder())
        self.metrics = ServeMetrics(flight=self.flight)
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self._on_failure = on_failure
        self.max_dispatch_retries = int(max_dispatch_retries)
        if self.max_dispatch_retries < 0:
            raise ValueError(f"max_dispatch_retries must be >= 0, got "
                             f"{max_dispatch_retries}")
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self.recover_on_hang = bool(recover_on_hang)
        self.max_recoveries = int(max_recoveries)
        self.record_store = record_store
        self.run_id = run_id or \
            f"{obs_record.new_run_id('serve')}-e{next(_ENGINE_SEQ)}"
        self._sleep = _sleep
        self._draining = False
        self._closed = False
        # set by the Heartbeat monitor thread, consumed at the next
        # step boundary by the step thread (which owns the arena)
        self._recover_flag = threading.Event()
        self._recoveries = 0
        self._incident_seq = itertools.count()
        self._tick_ewma: Optional[float] = None   # measured step() wall s
        # measured accepted-tokens-per-tick PER SLOT (EWMA): 1.0 for a
        # plain engine by construction, up to spec_k + 1 under
        # speculation — the shed eta divides by it so a spec engine
        # (whose queued requests reach their first token sooner because
        # slots drain faster) does not over-shed against a 1-token/tick
        # assumption (scheduler.eta_first_token)
        self._tpt_ewma: Optional[float] = None
        # admission-cadence hint from an external driver (the
        # disaggregated Router pushes its measured round time here):
        # the shed eta uses the slower of this and the engine's own
        # tick EWMA, so a worker stepped once per router round does not
        # under-estimate queue wait by (round / own-tick)
        self.tick_hint_s: Optional[float] = None

        # weights snapshotted once (same pattern as _gen_setup); decode
        # is weight-read bound, so an optional one-time bf16 cast halves
        # per-token HBM traffic on TPU
        params = {n: t.data for n, t in model.get_params().items()}
        if not params:
            raise ValueError(
                "model has no initialized params — call model.compile() "
                "(or run one forward) before building a ServeEngine")
        buffers = {n: t.data for n, t in model._get_buffers().items()}
        arena_dtype = None
        if param_dtype is not None:
            params = {n: (a.astype(param_dtype)
                          if jnp.issubdtype(a.dtype, jnp.floating) else a)
                      for n, a in params.items()}
            # the arena must match the dtype init_caches picks under the
            # CAST params inside the prefill trace (models size their
            # caches off the bound weights' dtype) — otherwise the
            # block scatter type-mismatches at trace time.  eval_shape
            # under the cast binding reads that dtype without allocating.
            with _bound(model, params, buffers):
                spec = jax.eval_shape(lambda: model.init_caches(1, 2))
            arena_dtype = jax.tree.leaves(spec)[0].dtype
        self._params, self._buffers = params, buffers
        # draft weights snapshotted the same way (param_dtype applies to
        # the draft too — decode AND verify are weight-read bound)
        if draft_model is not None:
            dparams = {n: t.data for n, t in draft_model.get_params().items()}
            if not dparams:
                raise ValueError(
                    "draft model has no initialized params — call "
                    "draft.compile() (or run one forward) before "
                    "building a speculative ServeEngine")
            dbuffers = {n: t.data
                        for n, t in draft_model._get_buffers().items()}
            if param_dtype is not None:
                dparams = {n: (a.astype(param_dtype)
                               if jnp.issubdtype(a.dtype, jnp.floating)
                               else a)
                           for n, a in dparams.items()}
            self._dparams, self._dbuffers = dparams, dbuffers
        else:
            self._dparams = self._dbuffers = None
        # arena construction args kept for recovery rebuilds
        self._num_slots, self._max_len = num_slots, max_len
        self._block_size, self._num_blocks = block_size, num_blocks
        self._arena_dtype = arena_dtype
        # KV memory hierarchy (ISSUE 17, serve/mem.py): arena storage
        # formats + the host-RAM spill tier for evicted prefix blocks.
        # The SpillStore is content-addressed (chain keys), so it
        # SURVIVES arena recovery — _recover hands the same store to
        # the fresh pool and a tenant's spilled system prompt outlives
        # even a rebuild.
        self._kv_dtype = serve_mem.normalize_kv_dtype(kv_dtype)
        self._draft_kv_dtype = (self._kv_dtype if draft_kv_dtype is None
                                else serve_mem.normalize_kv_dtype(
                                    draft_kv_dtype))
        if spill_blocks is not None and spill_blocks < 1:
            raise ValueError(
                f"spill_blocks must be >= 1 (or None to disable the "
                f"spill tier), got {spill_blocks}")
        self._spill = (serve_mem.SpillStore(spill_blocks)
                       if spill_blocks is not None else None)
        self.pool = BlockPool(model, num_slots, max_len,
                              block_size=block_size, num_blocks=num_blocks,
                              dtype=arena_dtype, draft_model=draft_model,
                              kv_dtype=self._kv_dtype,
                              draft_kv_dtype=self._draft_kv_dtype,
                              spill=self._spill)
        self._wire_spill()

        self._running: Dict[int, Request] = {}      # slot -> request
        # device-resident per-slot last tokens: written by prefill (the
        # request's first token) and decode (each next token); the host
        # only ever FETCHES this small int vector — tokens are never
        # uploaded, so the decode hot loop is one dispatch + one tiny
        # fetch per tick
        self._toks = jnp.zeros((num_slots,), jnp.int32)

        # ---- the exactly-two compiled programs --------------------------
        # (plus the optional third: the fixed-shape handoff gather a
        # disaggregated tier uses to move a finished prefill's blocks —
        # compiled lazily, only on the first handoff)
        if programs is not None:
            if programs.model_ref is not model:
                raise ValueError(
                    "programs= sharing requires the SAME model object "
                    "(the jitted closures capture its cached forward)")
            if programs.block_size != self.pool.block_size:
                raise ValueError(
                    f"programs= sharing requires matching block_size "
                    f"(template {programs.block_size}, this engine "
                    f"{self.pool.block_size})")
            if programs.draft_ref is not draft_model or \
                    programs.spec_k != self.spec_k:
                raise ValueError(
                    "programs= sharing requires the SAME draft model "
                    "object and spec_k (the verify program's closures "
                    f"capture both; template spec_k={programs.spec_k}, "
                    f"this engine spec_k={self.spec_k})")
            if programs.kv_dtype != self._kv_dtype or \
                    programs.draft_kv_dtype != self._draft_kv_dtype:
                raise ValueError(
                    "programs= sharing requires matching arena storage "
                    "formats (template kv_dtype="
                    f"{programs.kv_dtype!r}/draft "
                    f"{programs.draft_kv_dtype!r}, this engine "
                    f"{self._kv_dtype!r}/{self._draft_kv_dtype!r}) — a "
                    "mismatch would silently retrace every program "
                    "against the other arena layout instead of sharing")
            self._prefill = programs.prefill
            self._decode = programs.decode
            self._handoff = programs.handoff
            self._verify = programs.verify
            return
        bs = self.pool.block_size
        resume = resume_step(model)

        from . import spec as spec_mod

        def prefill_chunk(params, buffers, ids, pos, last_idx, slot,
                          tables, toks, caches):
            # one block-aligned chunk of one request's prompt: gather
            # the slot's dense view, run the cached forward at the
            # traced offset, pick the chunk's last valid token
            # in-program (only the final chunk's pick survives), and
            # scatter the ONE block this chunk filled back to the arena
            # (the gather/forward/scatter halves are the SAME helpers
            # the speculative prefill composes — serve/spec.py — so
            # the two prefill programs' semantics cannot drift apart)
            row = jax.lax.dynamic_index_in_dim(tables, slot, axis=0,
                                               keepdims=True)   # (1, MB)
            logits, dense = spec_mod.resume_on_row(
                resume, params, buffers, ids, pos, row, caches)
            last = jax.lax.dynamic_slice_in_dim(
                logits, last_idx, 1, axis=1)[:, 0, :]
            # greedy pick in-program (jnp.argmax — bit-identical to
            # _pick_impl's temperature-0 branch in generate())
            tok = jnp.argmax(last, axis=-1).astype(jnp.int32)[0]
            toks = toks.at[slot].set(tok)
            new = spec_mod.scatter_chunk(row, pos, caches, dense, bs)
            return toks, new

        dec = decode_step(model)

        def decode_paged(params, buffers, toks, pos, active, tables,
                         caches):
            # inactive slots are masked: position clamped to 0 and the
            # write redirected to the null block (their table row may
            # point at blocks now owned by OTHER requests, so —
            # unlike the fixed arena — scribbling through it is not
            # harmless), and their token entry frozen so nothing
            # downstream reads a garbage argmax
            posc = jnp.where(active, pos, 0)
            dense = [kv_ops.gather_block_kv(ck, cv, tables)
                     for ck, cv in caches]
            logits, dense = dec(params, buffers, toks[:, None], posc,
                                dense)
            picked = jnp.argmax(logits.astype(jnp.float32),
                                axis=-1).astype(jnp.int32)
            new_toks = jnp.where(active, picked, toks)
            new_pos = jnp.where(active, pos + 1, pos)
            wb = jnp.take_along_axis(tables, (posc // bs)[:, None],
                                     axis=1)[:, 0]
            wb = jnp.where(active, wb, 0)
            off = jnp.where(active, posc % bs, 0)

            def row_at(c, p):
                return jax.lax.dynamic_slice_in_dim(c, p, 1, axis=0)[0]

            new = []
            for (ck, cv), (dk, dv) in zip(caches, dense):
                k_tok = jax.vmap(row_at)(dk, posc)       # (S, K, D)
                v_tok = jax.vmap(row_at)(dv, posc)
                new.append(kv_ops.scatter_token_kv(ck, cv, wb, off,
                                                   k_tok, v_tok))
            return new_toks, new_pos, new

        def handoff_gather(tables, slot, caches):
            # the disaggregated tier's KV handoff source: ONE slot's
            # dense per-layer view gathered through its block-table row
            # (ops.kv_cache.gather_block_kv — no tensor reshaping).
            # The arena is NOT donated: a failed handoff must leave the
            # source caches valid so the router can re-route.
            row = jax.lax.dynamic_index_in_dim(tables, slot, axis=0,
                                               keepdims=True)   # (1, MB)
            return [kv_ops.gather_block_kv(ck, cv, row)
                    for ck, cv in caches]

        if draft_model is not None:
            # speculative engine: the prefill program also writes the
            # draft arena (both caches donated), and the VERIFY program
            # (serve/spec.py) replaces the per-tick decode — the plain
            # decode program stays as the serve.verify fault-fallback,
            # so the fixed compiled set is (prefill, decode, verify,
            # handoff), asserted via spec_compiled_counts()
            self._prefill = jax.jit(
                spec_mod.make_spec_prefill(model, draft_model, bs),
                donate_argnums=(10, 11))
            self._verify = jax.jit(
                spec_mod.make_verify(model, draft_model, self.spec_k, bs),
                donate_argnums=(8, 9))
        else:
            self._prefill = jax.jit(prefill_chunk, donate_argnums=(8,))
            self._verify = None
        self._decode = jax.jit(decode_paged, donate_argnums=(6,))
        self._handoff = jax.jit(handoff_gather)

    # -- introspection ----------------------------------------------------
    def compiled_counts(self):
        """(prefill, decode) jit-cache entry counts — the no-recompile
        invariant says both stay at 1 after warmup (tested via
        tools.lint.hlo.assert_program_count, shared with the HLO gate).
        When programs are shared across a worker pool these are the
        SHARED caches, so the invariant covers the whole tier at once."""
        return (self._prefill._cache_size(), self._decode._cache_size())

    def handoff_compiled_count(self) -> int:
        """Jit-cache entry count of the optional third program (the
        disaggregated handoff gather): 0 until the first handoff, 1
        after — never more (same fixed shapes as decode's inputs)."""
        return self._handoff._cache_size()

    def spec_compiled_counts(self):
        """(prefill, decode, verify, handoff) jit-cache entry counts —
        the FIXED PROGRAM SET invariant of ISSUE 13: a speculative
        engine's whole serving lifetime compiles exactly the asserted
        set and nothing else.  ``decode`` is 0 until a ``serve.verify``
        fault forces a plain-decode fallback tick, ``handoff`` is 0
        outside a disaggregated tier; no entry ever exceeds 1."""
        return (self._prefill._cache_size(), self._decode._cache_size(),
                self._verify._cache_size() if self._verify is not None
                else 0,
                self._handoff._cache_size())

    def programs(self) -> SharedPrograms:
        """The engine's compiled-program bundle, lendable to another
        same-model/same-block-size engine via ``programs=`` — see
        :class:`SharedPrograms`."""
        return SharedPrograms(self.model, self.pool.block_size,
                              self._prefill, self._decode, self._handoff,
                              self.draft_model, self.spec_k, self._verify,
                              self._kv_dtype, self._draft_kv_dtype)

    def lower_programs(self, names=None):
        """jax ``Lowered`` handles of the exactly-two programs (keyed
        ``prefill_chunk`` / ``decode``) plus the optional third
        (``handoff_gather``, the disaggregated tier's KV handoff
        source) and — on a speculative engine — ``verify``; the hook
        ``tools/lint/hlo.py`` compiles to optimized HLO and audits
        (fusions, donation of the KV arena, op histogram).  ``names``
        restricts the set (the gate lowers only ``verify`` from its
        spec engine — tracing the others there would be pure waste).
        Lowering is abstract: nothing executes, nothing is donated,
        and the jit caches (:meth:`compiled_counts`) are untouched.
        The traced shapes are exactly the runtime dispatch shapes, so
        the audited modules ARE the serving modules."""
        bs = self.pool.block_size
        zero = jnp.asarray(0, jnp.int32)

        def lower_prefill():
            if self._verify is not None:
                return self._prefill.lower(
                    self._params, self._buffers, self._dparams,
                    self._dbuffers, jnp.zeros((1, bs), jnp.int32),
                    zero, jnp.asarray(bs - 1, jnp.int32), zero,
                    self.pool.tables, self._toks, self.pool.caches,
                    self.pool.draft_caches)
            return self._prefill.lower(
                self._params, self._buffers, jnp.zeros((1, bs), jnp.int32),
                zero, jnp.asarray(bs - 1, jnp.int32), zero,
                self.pool.tables, self._toks, self.pool.caches)

        def lower_handoff():
            caches = (self.pool.caches + self.pool.draft_caches
                      if self._verify is not None else self.pool.caches)
            return self._handoff.lower(self.pool.tables, zero, caches)

        def lower_decode():
            return self._decode.lower(
                self._params, self._buffers, self._toks, self.pool.pos,
                self.pool.active, self.pool.tables, self.pool.caches)

        def lower_verify():
            return self._verify.lower(
                self._params, self._buffers, self._dparams,
                self._dbuffers, self._toks, self.pool.pos,
                self.pool.active, self.pool.tables, self.pool.caches,
                self.pool.draft_caches)

        thunks = {"prefill_chunk": lower_prefill, "decode": lower_decode,
                  "handoff_gather": lower_handoff}
        if self._verify is not None:
            thunks["verify"] = lower_verify
        wanted = thunks if names is None else {
            n: thunks[n] for n in names}
        return {name: thunk() for name, thunk in wanted.items()}

    @property
    def pending(self) -> int:
        """Requests still in flight (queued + running)."""
        return self.sched.depth + len(self._running)

    # -- disaggregated-tier hooks (serve/disagg) ---------------------------
    def running_items(self) -> List[Tuple[int, Request]]:
        """(slot, request) pairs currently occupying slots, slot order —
        the router's per-tick view of what a prefill worker has ready to
        hand off (a snapshot: handing off mutates ``_running``)."""
        return sorted(self._running.items())

    def withdraw(self, slot: int) -> Request:
        """Remove a RUNNING request from this engine without finishing
        it: the slot and its blocks are released, the request keeps its
        prompt + tokens-so-far and goes back to QUEUED — the router's
        re-route primitive (greedy decode makes the replay elsewhere
        reproduce the exact stream, same argument as preemption)."""
        req = self._running.pop(slot)
        self.pool.release(slot)
        req.slot = None
        req.state = QUEUED
        return req

    def can_accept_handoff(self, pkg) -> bool:
        """Whether this engine could :meth:`inject_handoff` ``pkg``
        right now (free slot + coverable blocks, prefix sharing
        counted) — side-effect free; see serve/disagg/handoff.py."""
        from .disagg import handoff as _handoff_mod
        return _handoff_mod.can_accept(self, pkg)

    def extract_handoff(self, slot: int):
        """Pull a finished prefill out of this engine as a
        :class:`~singa_tpu.serve.disagg.handoff.HandoffPackage`:
        the slot's blocks are gathered through the fixed-shape
        ``handoff_gather`` program (the optional third compiled
        program), then slot and blocks are released here — the
        request now lives in the package until injected elsewhere."""
        from .disagg import handoff as _handoff_mod
        return _handoff_mod.extract(self, slot)

    def inject_handoff(self, pkg) -> bool:
        """Admit a prefilled request arriving from another engine:
        blocks whose prefix chain keys are already resident map
        copy-free (refcounts and keys transfer with the blocks), the
        rest are scattered into freshly allocated blocks, and the
        request continues decoding here mid-stream.  False when
        capacity is lacking (the router parks the handoff)."""
        from .disagg import handoff as _handoff_mod
        return _handoff_mod.inject(self, pkg)

    # -- submission --------------------------------------------------------
    def submit(self, prompt_ids, *, max_new_tokens: int,
               deadline_s: Optional[float] = None,
               eos_id: Optional[int] = None,
               on_token=None,
               trace_id: Optional[str] = None) -> RequestHandle:
        """Queue one generation request; returns its handle.

        Raises :class:`QueueFull` when admission control refuses the
        request — the wait queue is at capacity.  Admission out of the
        queue into slots happens only at ``step()`` boundaries, so a
        burst of more than ``max_queue`` un-stepped submissions is
        rejected even while slots are free (size ``max_queue`` for the
        largest burst to absorb; default ``2 * num_slots``).  Raises
        ``ValueError`` when the request cannot ever fit the arena
        (prompt + budget past ``max_len`` — the guarantee that decode
        never writes past a request's block budget is enforced here, at
        the door; chunked prefill itself has no separate prompt cap).
        Raises :class:`EngineClosed` while draining or after
        ``close()``."""
        if self._closed:
            raise EngineClosed("submit() on a closed engine")
        if self._draining:
            raise EngineClosed(
                "engine is draining — new submissions are refused while "
                "in-flight requests complete")
        req = Request(prompt_ids, max_new_tokens, deadline_s, eos_id,
                      on_token)
        # one trace per request (ISSUE 11): every event the engine emits
        # about this request — admission, prefix hit, prefill chunks,
        # first token, decode deliveries, preemption, quarantine,
        # finish/shed/evict — carries this id, so the whole request is
        # reconstructable as a single trace (handle.trace_id).  A
        # caller-supplied ``trace_id`` (the disaggregated Router) keeps
        # ONE id alive across every worker the request touches, which
        # is what makes the cross-worker timeline a single trace.
        req.trace_id = trace_id or f"{self.run_id}/r{req.rid}"
        p = req.prompt.size
        # a speculative engine needs spec_k tokens of arena headroom:
        # the request's LAST verify round may still write a full
        # k+1-position window past its final accepted token, and those
        # writes must stay inside the slot's dense view
        if p + req.max_new_tokens + self.spec_k > self.pool.max_len:
            k_note = (f" + spec_k ({self.spec_k})" if self.spec_k
                      else "")
            raise ValueError(
                f"prompt ({p}) + max_new_tokens ({req.max_new_tokens})"
                f"{k_note} = {p + req.max_new_tokens + self.spec_k} "
                f"exceeds max_len ({self.pool.max_len})")
        with obs_trace.activate(req.trace_id):
            try:
                self.sched.offer(req)
            except QueueFull:
                self.metrics.on_reject()
                raise
            self.metrics.on_submit()
        return req.handle

    def resubmit(self, prompt_ids, tokens, *, max_new_tokens: int,
                 deadline_s: Optional[float] = None,
                 eos_id: Optional[int] = None,
                 trace_id: Optional[str] = None,
                 ttft_s: Optional[float] = None) -> RequestHandle:
        """Re-admit a request that was already in flight SOMEWHERE ELSE
        (a drained or dead worker in the multi-process tier): the
        replay re-route primitive across process boundaries.  The
        request re-enters at the HEAD of the queue with its generated
        ``tokens`` pre-installed, so the next admission re-prefills
        prompt + tokens and greedy replay idempotence continues the
        stream bit-identically — the same machinery ``withdraw()`` +
        ``requeue_front()`` provide in-process, reconstructed here from
        the supervisor's host mirror of the request.  ``ttft_s`` (the
        original first-token latency, when one was already delivered)
        is preserved so a re-route never *improves* a reported TTFT.
        Not counted as a new submission in the run ledger — the request
        was submitted once, on the worker that lost it."""
        if self._closed:
            raise EngineClosed("resubmit() on a closed engine")
        if self._draining:
            raise EngineClosed(
                "engine is draining — new submissions are refused while "
                "in-flight requests complete")
        req = Request(prompt_ids, max_new_tokens, deadline_s, eos_id,
                      None)
        req.tokens = [int(t) for t in tokens]
        req.trace_id = trace_id or f"{self.run_id}/r{req.rid}"
        req.ttft_s = ttft_s
        p = req.prompt.size
        if p + req.max_new_tokens + self.spec_k > self.pool.max_len:
            raise ValueError(
                f"prompt ({p}) + max_new_tokens ({req.max_new_tokens}) "
                f"exceeds max_len ({self.pool.max_len})")
        with obs_trace.activate(req.trace_id):
            self.sched.requeue_front([req])
            self.flight.note("counter", "serve.resubmit", rid=req.rid,
                             replayed=len(req.tokens))
        return req.handle

    # -- the engine loop ---------------------------------------------------
    def step(self, *, decode: bool = True) -> int:
        """One continuous-batching tick: recovery (if requested by the
        hang watchdog) → deadline eviction → overload shedding →
        admission (prefill queued requests into free slots while free
        blocks cover them) → block-table growth → one decode over all
        active slots.  Returns the number of tokens delivered.

        ``decode=False`` stops after admission — the disaggregated
        tier's PREFILL-WORKER tick: freshly prefilled requests stay in
        their slots (blocks intact) for the router to hand off to a
        decode worker instead of decoding here.  Deadline eviction
        still applies to parked requests, so a handoff the decode pool
        cannot absorb in time is shed by the same machinery as any
        other overload."""
        if self._closed:
            raise EngineClosed("step() on a closed engine")
        with events.span("serve.step"):
            now = time.monotonic()
            delivered = 0

            # 0. hang recovery — the Heartbeat monitor thread can only
            #    REQUEST it; the rebuild must run here, on the step
            #    thread, which owns the arena
            if self._recover_flag.is_set():
                self._recover_flag.clear()
                self._recover("heartbeat")

            # 1. deadline eviction — queued requests that died waiting
            #    and running requests past their deadline vacate first,
            #    so their slots/blocks are admittable this same tick
            for req in self.sched.expire_queued(now):
                with obs_trace.activate(req.trace_id):
                    self.metrics.on_evict("deadline")
            for slot in [s for s, r in self._running.items()
                         if r.expired(now)]:
                req = self._running[slot]
                req.finish_reason = "deadline"
                self._finalize(slot, evicted=True)

            # 1b. deadline-aware overload shedding — queued requests
            #     that cannot plausibly deliver a first token before
            #     their deadline are shed before burning a prefill
            for req in self.sched.shed_overload(now, self._eta_first_token):
                with obs_trace.activate(req.trace_id):
                    self.metrics.on_evict("shed")

            # 2. admission — prefill into free slots between decode
            #    steps.  A slot row is not enough: the head-of-queue
            #    request must also be coverable by free + evictable
            #    blocks (FIFO: a too-big head blocks the line rather
            #    than being overtaken)
            while self.pool.free_count:
                req = self.sched.peek()
                if req is None or not self._admittable(req):
                    break
                self.sched.pop_for_admission()
                delivered += self._admit(req)

            # 3. block-table growth + one decode tick over the whole
            #    arena; a decode (or a decode-time block allocation)
            #    that died past its retry budget escalates to an arena
            #    rebuild + re-prefill instead of crashing the engine
            if self._running and decode:
                try:
                    self._ensure_blocks()
                    if self._running:
                        delivered += (self._spec_tick()
                                      if self._verify is not None
                                      else self._decode_tick())
                except (RuntimeError, OSError) as e:
                    if isinstance(e, failure.FailureDetected):
                        raise
                    self._recover(f"decode: {type(e).__name__}: {e}")

            # settle spill payloads onto host numpy AFTER the tick's
            # token-extraction sync: the D2H copies are already done,
            # so this collects without waiting, and device-side spill
            # buffers live at most one tick
            if self._spill is not None:
                self._spill.settle()

            self.metrics.on_step(self.sched.depth, self.pool.active_count,
                                 self.pool.blocks_in_use,
                                 self.pool.blocks_in_use_bytes)
            dt = time.monotonic() - now
            self._tick_ewma = dt if self._tick_ewma is None else \
                0.8 * self._tick_ewma + 0.2 * dt
        return delivered

    def _eta_first_token(self, position: int) -> float:
        """Seconds until the queued request at ``position`` could
        plausibly deliver its first token — delegates to the shared
        :func:`scheduler.eta_first_token` model with this engine's
        admission period: the slower of the measured tick EWMA and the
        external ``tick_hint_s`` a multi-pool driver (the disaggregated
        Router) pushes, so a worker that only gets one admission
        opportunity per router round sheds against the ROUND cadence,
        not its own optimistic step time.  0.0 before any timing
        evidence exists — shedding never fires blind."""
        tick = self._tick_ewma
        if self.tick_hint_s:
            tick = (self.tick_hint_s if tick is None
                    else max(tick, self.tick_hint_s))
        if tick is None:
            return 0.0
        return eta_first_token(position, free_slots=self.pool.free_count,
                               wave_size=self.pool.num_slots, tick_s=tick,
                               tokens_per_tick=self._tpt_ewma or 1.0)

    def run_until_idle(self, max_steps: Optional[int] = None) -> None:
        """Drive ``step()`` until no request is queued or running.  With
        ``heartbeat_timeout_s`` set, a Heartbeat watchdog guards every
        tick — a hung decode (dead device, wedged tunnel) aborts cleanly
        instead of wedging the server, or, with ``recover_on_hang``,
        requests an arena rebuild + re-prefill at the next step
        boundary."""
        hb = Heartbeat(timeout=self.heartbeat_timeout_s,
                       on_failure=(self._hb_failure if self.recover_on_hang
                                   else self._on_failure)) \
            if self.heartbeat_timeout_s else None
        n = 0
        with hb if hb is not None else nullcontext():
            while self.pending:
                self.step()
                n += 1
                if hb is not None:
                    hb.beat(n)
                    if hb.fired and self.recover_on_hang:
                        # the monitor thread exits after firing once;
                        # re-arm it so a later hang in this same drive
                        # is also caught
                        hb.stop()
                        hb.start()
                if max_steps is not None and n >= max_steps:
                    break
        if not self.pending:
            # a fully drained system is proof the last recovery took —
            # give future incidents a fresh rebuild budget, and drop any
            # rebuild REQUEST a hang on the final tick left behind (the
            # late decode still delivered everything; rebuilding a
            # healthy idle arena at the next drive's first step would
            # burn recovery budget and record a bogus incident)
            self._recoveries = 0
            self._recover_flag.clear()

    def drain(self, max_steps: Optional[int] = None) -> None:
        """Stop accepting new requests and complete everything already
        in the system: queued requests still get admitted, in-flight
        slots decode to completion (or eviction).  ``submit()`` raises
        :class:`EngineClosed` from the moment drain begins — draining is
        one-way, the step before :meth:`close`.  Safe to call
        repeatedly."""
        self._draining = True
        self.run_until_idle(max_steps=max_steps)

    def close(self) -> None:
        """``drain()`` to idle, then release the engine: the arena and
        token buffer are dropped (freeing device memory) and every
        subsequent ``submit()``/``step()`` raises :class:`EngineClosed`.
        Idempotent."""
        if self._closed:
            return
        self.drain()
        self._closed = True
        self.pool = None
        self._toks = None

    def __enter__(self) -> "ServeEngine":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    # -- internals ---------------------------------------------------------
    def _wire_spill(self) -> None:
        """Point the pool's spill-tier callbacks at this engine: spill/
        prefetch accounting lands in the metrics, and an injected
        ``serve.spill`` fault produces a flight dump + incident record
        (the fault itself only DEGRADES — the block dies or the prefix
        re-prefills, streams are unchanged — but the evidence trail
        must still exist)."""
        if self.pool.spill is None:
            return
        self.pool.on_spill = self.metrics.on_spill
        self.pool.on_prefetch = self.metrics.on_prefetch
        self.pool.on_spill_fault = self._spill_fault

    def _spill_fault(self, op: str, exc: Exception) -> None:
        ref = self._flight_dump("serve.spill",
                                f"{op} fault: {type(exc).__name__}")
        self._incident("serve.spill", type(exc).__name__, f"op:{op}",
                       "degraded", 0, flight_ref=ref)

    #: dispatch site -> the cost model's program key (hlo.FLAGSHIP_
    #: PROGRAMS) the runtime-attribution ledger accumulates under; the
    #: handoff gather is timed at its own seam (serve/disagg/handoff.py
    #: ``_gather`` — it does not ride ``_dispatch``'s retry loop)
    _ATTR_PROGRAMS = {"serve.prefill": "prefill_chunk",
                      "serve.decode": "decode",
                      "serve.verify": "verify"}

    def _attr_program(self, site: str) -> str:
        """The ledger key one dispatch accumulates under.  An int8
        arena's decode is a DIFFERENT compiled program with its own
        cost-model row (the ``decode_int8`` flagship), so its runtime
        must reconcile against that row, not full-precision decode's."""
        if site == "serve.decode" and self._kv_dtype == "int8":
            return "decode_int8"
        return self._ATTR_PROGRAMS.get(site, site)

    def _dispatch(self, site: str, fn, args, **attrs):
        """One guarded jitted dispatch: the injection site fires first
        (host-side, BEFORE the call — the donated arena is still
        intact), and transient RuntimeError/OSError is retried with
        bounded exponential backoff.  Retry scope mirrors
        ``train.loop``: sound for dispatch-level transients (tunnel
        hiccup before launch, injected faults); a REAL mid-execution
        failure invalidates the donated arena, so retries fail too and
        the error escalates to the caller — quarantine for prefill,
        arena recovery for decode.

        With a runtime-attribution ledger installed (obs.attr), the
        SUCCESSFUL call is timed host-side and noted under the site's
        program key — failed attempts never pollute the distribution
        (a retried fault is the incident layer's story, not a slow
        program's).  With no ledger the only cost is one global read."""
        attempt = 0
        while True:
            try:
                faults.fire(site, attempt=attempt, **attrs)
                led = obs_attr.get()
                if led is None:
                    return fn(*args)
                t0 = time.perf_counter()
                out = fn(*args)
                led.note(self._attr_program(site),
                         time.perf_counter() - t0)
                return out
            except (RuntimeError, OSError) as e:
                if isinstance(e, failure.FailureDetected):
                    raise
                if attempt >= self.max_dispatch_retries:
                    raise
                delay = min(self.backoff_max,
                            self.backoff_base * (2 ** attempt))
                attempt += 1
                self.metrics.on_retry(site)
                self._sleep(delay)

    # -- paged-arena bookkeeping -------------------------------------------
    def _share_limit(self, req: Request) -> int:
        """How many leading blocks of this request's replay are
        ELIGIBLE for prefix sharing: full blocks wholly inside the
        ORIGINAL prompt (generated tokens are private), and never the
        whole replay — at least one suffix token must run prefill so
        the request has last-position logits to pick its first token
        from."""
        if not self.share_prefix:
            return 0
        bs = self.pool.block_size
        return min(req.prompt.size // bs,
                   (req.replay_ids().size - 1) // bs)

    def _blocks_needed(self, req: Request, n_shared: int) -> int:
        """Fresh blocks an admission must allocate: coverage for the
        replay plus the first decode position, minus the shared
        prefix."""
        replay = req.replay_ids().size
        return replay // self.pool.block_size + 1 - n_shared

    def _req_keys(self, req: Request) -> list:
        """The request's prefix chain keys, computed once (they depend
        only on the immutable prompt) — a head-of-queue request waiting
        on free blocks is probed every step and must not re-hash its
        whole prefix each time."""
        if not self.share_prefix:
            return []
        if req.prefix_keys is None:
            req.prefix_keys = self.pool.prefix_keys(
                req.prompt, req.prompt.size // self.pool.block_size)
        return req.prefix_keys

    def _admittable(self, req: Request) -> bool:
        n_shared, n_lru = self.pool.probe_prefix(
            req.prompt, self._share_limit(req), keys=self._req_keys(req))
        # claiming shared blocks out of the evictable LRU consumes
        # available_blocks too — only what remains can cover the fresh
        # allocation
        return (self.pool.available_blocks - n_lru
                >= self._blocks_needed(req, n_shared))

    def _alloc_blocks(self, n: int, rid: int) -> Optional[List[int]]:
        """Claim ``n`` blocks through the ``serve.block_alloc``
        injection site (fires BEFORE the host-side allocation, so an
        injected error leaves refcounts untouched).  Returns None when
        the pool genuinely cannot cover ``n`` — the preemption cue."""
        faults.fire("serve.block_alloc", n=n, rid=rid)
        return self.pool.alloc_blocks(n)

    def _admit(self, req: Request) -> int:
        # the whole admission — block claim, prefix hit, prefill chunks,
        # first-token delivery, quarantine on failure — runs under the
        # request's trace, so each of those events carries its id
        with obs_trace.activate(req.trace_id):
            return self._admit_traced(req)

    def _admit_traced(self, req: Request) -> int:
        slot = self.pool.alloc_slot()
        assert slot is not None, "admission with no free slot"
        # replay_ids == prompt for a fresh request; for a request
        # re-admitted by preemption or arena recovery it is prompt +
        # tokens-so-far, whose greedy prefill pick IS the next decode
        # token — the re-prefill is idempotent
        replay = req.replay_ids()
        P = replay.size
        bs = self.pool.block_size
        first = not req.tokens
        owned: List[int] = []
        shared_ids: List[int] = []
        mapped = False
        # the allocation site fires once (no retry loop); only the
        # prefill dispatches below go through _dispatch's backoff —
        # quarantine must attribute the failure to the seam that died
        fail_site, fail_attempts = "serve.block_alloc", 1
        try:
            n_shared, shared_ids = self.pool.match_prefix(
                req.prompt, self._share_limit(req),
                keys=self._req_keys(req))
            owned = self._alloc_blocks(
                self._blocks_needed(req, n_shared), req.rid) or []
            if len(owned) < self._blocks_needed(req, n_shared):
                # _admittable() held when we were popped and nothing
                # ran since — an all-or-nothing alloc can only come up
                # short through a bug; fail THIS request loudly
                raise RuntimeError("block allocation came up short")
            fail_site = "serve.prefill"
            fail_attempts = self.max_dispatch_retries + 1
            self.pool.map_slot(slot, shared_ids + owned)
            mapped = True
            start0 = n_shared * bs
            if n_shared:
                self.metrics.on_prefix_hit(start0)
            with events.span("serve.prefill", slot=slot, prompt=P,
                             shared=start0):
                for start in range(start0, P, bs):
                    ids = np.zeros((1, bs), np.int32)
                    chunk = replay[start:start + bs]
                    ids[0, :chunk.size] = chunk
                    if self._verify is not None:
                        # spec engine: the ONE prefill program writes
                        # the chunk into BOTH arenas (target + draft)
                        (self._toks, self.pool.caches,
                         self.pool.draft_caches) = self._dispatch(
                            "serve.prefill", self._prefill,
                            (self._params, self._buffers, self._dparams,
                             self._dbuffers, jnp.asarray(ids),
                             jnp.asarray(start, jnp.int32),
                             jnp.asarray(chunk.size - 1, jnp.int32),
                             jnp.asarray(slot, jnp.int32),
                             self.pool.tables, self._toks,
                             self.pool.caches, self.pool.draft_caches),
                            rid=req.rid)
                        continue
                    self._toks, self.pool.caches = self._dispatch(
                        "serve.prefill", self._prefill,
                        (self._params, self._buffers, jnp.asarray(ids),
                         jnp.asarray(start, jnp.int32),
                         jnp.asarray(chunk.size - 1, jnp.int32),
                         jnp.asarray(slot, jnp.int32),
                         self.pool.tables, self._toks, self.pool.caches),
                        rid=req.rid)
                tok = int(np.asarray(self._toks)[slot])  # singalint: disable=SGL008 the designed per-admission sync: one num_slots-int fetch delivers the prefill token
        except (RuntimeError, OSError) as e:
            if isinstance(e, failure.FailureDetected):
                raise
            # the injected/transient failure fired before a dispatch
            # touched anything irreversible: unwind this request's
            # claims (refcounts included) and fail only THIS request,
            # not the engine
            if mapped:
                self.pool.release(slot)
            else:
                self.pool.unref_shared(shared_ids)
                self.pool.free_blocks(owned)
                self.pool.release_slot_row(slot)
            self._quarantine(req, e, fail_site, fail_attempts)
            return 0
        if self.share_prefix:
            self.pool.register_prefix(req.prompt, slot,
                                      req.prompt.size // bs,
                                      keys=self._req_keys(req))
        self.pool.activate(slot, P)
        req.slot = slot
        req.state = RUNNING
        self._running[slot] = req
        if first:
            # preemption/recovery re-prefills count under their own
            # counters, not here — ``admitted`` stays comparable to
            # ``submitted``
            self.metrics.on_admit()
        done = req.deliver(tok)       # prefill yields the (next) token
        self.metrics.on_deliver(req.rid, len(req.tokens))
        if first:
            self.metrics.on_first_token(req.ttft_s)
        if req.on_token is not None:
            req.on_token(tok, req.handle)
        if done:
            self._finalize(slot)
        return 1

    def _quarantine(self, req: Request, err: Exception,
                    site: str = "serve.prefill",
                    attempts: Optional[int] = None) -> None:
        """Repeatedly-poisoned prefill/admission: surface a per-request
        failure status (handle.failed / handle.error), never an engine
        crash.  ``site``/``attempts`` name the seam that actually died
        (block allocation fires once; prefill retries with backoff) so
        the incident record stays honest evidence."""
        if attempts is None:
            attempts = self.max_dispatch_retries + 1
        req.state = FAILED
        req.finish_reason = "quarantined"
        req.error = (f"{site} failed after {attempts} attempt(s): "
                     f"{type(err).__name__}: {err}")
        self.metrics.on_quarantine()
        ref = self._flight_dump(site, f"quarantine req:{req.rid}")
        self._incident(site, type(err).__name__,
                       f"req:{req.rid}", "quarantined", attempts,
                       flight_ref=ref)
        warnings.warn(f"serve: request {req.rid} quarantined: "
                      f"{req.error}", stacklevel=2)

    def _ensure_blocks(self) -> None:
        """Decode-time growth: before the tick, every running slot
        whose next write position crosses into an unmapped block gets
        one more block — preempting the youngest running request when
        the pool is exhausted (its blocks are released, it re-queues at
        the head and replays later; greedy decode keeps its stream
        bit-identical)."""
        for slot in sorted(self._running):
            req = self._running.get(slot)
            if req is None:
                continue
            bs = self.pool.block_size
            # a verify round writes up to position pos + spec_k (the
            # full k+1 window), so a speculative slot needs its blocks
            # mapped spec_k positions ahead of a plain one
            need = (req.prompt.size + len(req.tokens)
                    + self.spec_k) // bs + 1
            while slot in self._running and \
                    self.pool.mapped_count(slot) < need:
                got = self._alloc_blocks(1, req.rid)
                if got:
                    self.pool.append_block(slot, got[0])
                else:
                    self._preempt_youngest()

    def _preempt_youngest(self) -> None:
        victim_slot = max(self._running,
                          key=lambda s: self._running[s].rid)
        req = self._running.pop(victim_slot)
        self.pool.release(victim_slot)
        req.state = QUEUED
        req.slot = None
        self.sched.requeue_front([req])
        with obs_trace.activate(req.trace_id):
            self.metrics.on_preempt()

    def _decode_tick(self) -> int:
        t0 = time.perf_counter()
        with events.span("serve.decode", active=len(self._running)):
            self._toks, new_pos, self.pool.caches = self._dispatch(
                "serve.decode", self._decode,
                (self._params, self._buffers, self._toks,
                 self.pool.pos, self.pool.active, self.pool.tables,
                 self.pool.caches),
                active=len(self._running))
            toks = np.asarray(self._toks)    # singalint: disable=SGL008 the designed per-tick sync: ONE num_slots-int fetch per decode dispatch is the engine's hot-loop host traffic
        self.pool.pos = new_pos
        dt = time.perf_counter() - t0
        delivered = 0
        for slot in list(self._running):
            req = self._running[slot]
            tok = int(toks[slot])
            # one batched decode dispatch delivers to many requests;
            # the per-request section runs under each request's trace
            # so its token events attribute correctly
            with obs_trace.activate(req.trace_id):
                done = req.deliver(tok)
                self.metrics.on_token(dt)
                self.metrics.on_deliver(req.rid, len(req.tokens))
                self.metrics.on_slot_dispatch(1)
            if req.on_token is not None:
                req.on_token(tok, req.handle)
            delivered += 1
            if done:
                self._finalize(slot)
        self._note_tpt(delivered, delivered)
        return delivered

    def _spec_tick(self) -> int:
        """One speculative verify round (serve/spec.py) — with a
        PLAIN-DECODE fallback when the verify DISPATCH dies past its
        retry budget (injected ``serve.verify`` faults included): one
        target-correct token per slot still lands this tick, the
        accepted stream is unchanged (plain decode is the same target
        argmax), and only the draft cache takes a gap at the fallback
        position — a later accept-rate cost, never a correctness one.
        Only :class:`~singa_tpu.serve.spec.VerifyDispatchFailed` takes
        this path — nothing was committed yet, so a plain tick on the
        untouched arena is safe.  A failure AFTER the dispatch (result
        fetch, delivery) is half-committed and propagates to step()'s
        arena-recovery handler instead, as does a fallback tick that
        ALSO fails."""
        from . import spec as spec_mod
        participants = len(self._running)
        try:
            delivered = spec_mod.verify_round(self)
        except spec_mod.VerifyDispatchFailed as e:
            self.metrics.on_spec_fallback()
            warnings.warn(
                f"serve: verify round failed past retries "
                f"({type(e).__name__}: {e}); falling back to plain "
                f"decode for this tick", stacklevel=2)
            return self._decode_tick()
        self._note_tpt(delivered, participants)
        return delivered

    def _note_tpt(self, delivered: int, participants: int) -> None:
        """Fold one tick's accepted-tokens-per-slot into the EWMA the
        shed eta consumes (scheduler.eta_first_token tokens_per_tick)."""
        if not participants:
            return
        tpt = delivered / participants
        self._tpt_ewma = tpt if self._tpt_ewma is None else \
            0.8 * self._tpt_ewma + 0.2 * tpt

    def _finalize(self, slot: int, evicted: bool = False) -> None:
        req = self._running.pop(slot)
        self.pool.release(slot)
        req.state = EVICTED if evicted else FINISHED
        with obs_trace.activate(req.trace_id):
            self.metrics.on_evict(req.finish_reason or "unknown")

    # -- recovery ----------------------------------------------------------
    def recover(self, reason: str = "requested") -> None:
        """Rebuild the arena — fresh block pool, block tables,
        refcounts, empty prefix cache — and re-prefill every in-flight
        request; the path behind Heartbeat hang detection, also
        callable directly after an external device event.  Each running
        request is requeued at the HEAD of the queue and re-prefilled
        from ``prompt + tokens-so-far``; greedy decode makes that
        replay idempotent, so however many times recovery runs, the
        final streams are bit-identical to an uninterrupted run.
        (Chunked prefill has no prompt-length cap below ``max_len``, so
        — unlike the PR 2 fixed arena — every in-flight replay is
        recoverable.)"""
        self._recover(reason)

    def _recover(self, reason: str) -> None:
        self._recoveries += 1
        if self._recoveries > self.max_recoveries:
            raise RuntimeError(
                f"serve engine exceeded max_recoveries="
                f"{self.max_recoveries} (last reason: {reason}) — the "
                f"fault is not transient; surfacing it instead of "
                f"rebuilding forever")
        with events.span("serve.recover", reason=reason):
            inflight = sorted(self._running.values(), key=lambda r: r.rid)
            self._running.clear()
            # fresh arena + tables + token buffer: same shapes/dtypes,
            # so the two compiled programs are reused — recovery never
            # recompiles.  The prefix cache dies with the old pool
            # (its blocks' contents are gone); re-prefills rebuild
            # tables and refcounts from scratch.
            # ... except what already SPILLED: the store is content-
            # addressed (chain keys), so its host-side payloads stay
            # valid for the fresh arena and survive the rebuild
            self.pool = BlockPool(self.model, self._num_slots,
                                  self._max_len,
                                  block_size=self._block_size,
                                  num_blocks=self._num_blocks,
                                  dtype=self._arena_dtype,
                                  draft_model=self.draft_model,
                                  kv_dtype=self._kv_dtype,
                                  draft_kv_dtype=self._draft_kv_dtype,
                                  spill=self._spill)
            self._wire_spill()
            self._toks = jnp.zeros((self._num_slots,), jnp.int32)
            requeue = []
            for req in inflight:
                if req.replay_ids().size >= self.pool.max_len:
                    # defensive: unreachable while submit() enforces
                    # prompt + budget <= max_len, but a replay that
                    # could never decode again must fail loudly, not
                    # silently truncate
                    req.state = FAILED
                    req.finish_reason = "unrecoverable"
                    req.error = (
                        f"cannot re-prefill after arena rebuild: prompt "
                        f"+ generated = {req.replay_ids().size} tokens "
                        f"leaves no room to decode under max_len "
                        f"({self.pool.max_len})")
                    # the request's terminal event must carry its trace
                    # like every other evict site — THIS request is the
                    # one the incident postmortem is about
                    with obs_trace.activate(req.trace_id):
                        self.metrics.on_evict("unrecoverable")
                        self._incident(
                            "serve.arena", reason, f"req:{req.rid}",
                            "unrecoverable", 0,
                            flight_ref=self._flight_dump(
                                "serve.arena",
                                f"unrecoverable req:{req.rid}"))
                else:
                    requeue.append(req)
            self.sched.requeue_front(requeue)
            self.metrics.on_recover(len(requeue))
            self._incident("serve.arena", reason,
                           f"inflight:{len(requeue)}", "recovered",
                           self._recoveries,
                           flight_ref=self._flight_dump(
                               "serve.arena", f"recovery: {reason}"))

    def _hb_failure(self, age: float, last_beat: int) -> None:
        """Heartbeat monitor-thread path (``recover_on_hang``): only
        REQUEST recovery — the step thread owns the arena and performs
        the rebuild at its next step boundary (a hung dispatch cannot be
        preempted from here anyway; an injected hang simply returns
        late).  A user ``on_failure`` still gets the observation."""
        events.counter("serve.hangs", 1, age_s=round(age, 3))
        # monitor thread: deliberately trace-less (the hang is an
        # engine-level observation, not any one request's)
        self.flight.note("counter", "serve.hangs", age_s=round(age, 3))
        self._recover_flag.set()
        if self._on_failure is not None:
            self._on_failure(age, last_beat)

    # -- durable incident records + flight dumps --------------------------
    def _flight_dump(self, site: str, reason: str) -> Optional[str]:
        """Dump the flight ring next to the record store and return the
        ``flight_ref`` (or None without a store) — the shared
        :func:`obs.flight.dump_for_store` contract; this thin wrapper
        exists so literal sites at call sites stay SGL009-checkable."""
        return obs_flight.dump_for_store(self.flight, site,
                                         self.record_store, reason)

    def _incident(self, site: str, fault: str, ref, outcome: str,
                  retries: int, flight_ref: Optional[str] = None) -> None:
        """Append one ``incident`` entry to the run-record store (when
        ``record_store`` is set).  Best-effort: the record is evidence,
        not a dependency — a full disk must not turn a survived fault
        into a crash."""
        events.counter("serve.incident", 1, site=site, outcome=outcome)
        self.flight.note("counter", "serve.incident", site=site,
                         outcome=outcome)
        if not self.record_store:
            return
        try:
            platform = jax.default_backend()
            dev = jax.devices()[0]
            payload = {"site": site, "fault": fault, "ref": ref,
                       "outcome": outcome, "retries": int(retries),
                       "engine_run": self.run_id}
            if flight_ref:
                payload["flight_ref"] = flight_ref
            entry = obs_record.new_entry(
                "incident", platform, platform != "tpu",
                getattr(dev, "device_kind", "") or platform,
                run_id=f"{self.run_id}-inc{next(self._incident_seq)}",
                payload=payload)
            obs_record.RunRecord(self.record_store).append(entry)
        except Exception as e:
            warnings.warn(f"could not append incident record: "
                          f"{type(e).__name__}: {e}", stacklevel=2)
