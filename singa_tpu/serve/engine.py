"""ServeEngine — continuous-batching inference over a slot arena.

The engine turns the one-session decode loop of
``models/_generate.py`` into a multi-request server while keeping the
training stack's single-compiled-module discipline: for a given
(model, num_slots, max_len) it compiles exactly TWO XLA programs —

* **prefill-into-slot** — one request's prompt (padded to
  ``prefill_len``, true length passed as a traced scalar) runs the
  model's cached forward against a fresh cache row, which is then
  written into the arena at a traced slot index.  Variable prompt
  lengths therefore never change the compiled shape.
* **decode-over-slots** — ONE token for every slot per dispatch, with
  per-slot positions: RoPE offsets, cache scatters and attention
  limits are all (num_slots,) vectors inside the program (the ops
  layer grew per-row variants for exactly this), and inactive slots
  are masked — their position is clamped to 0 and their logits zeroed,
  so a half-empty arena still runs the same program.

Both programs thread params/buffers as jit arguments through the same
``_bound`` rebinding as generation, so weights are never baked into the
executables, and both donate the arena, so cache memory is updated in
place.  Submitting, admitting and evicting requests are host-side index
updates — no recompilation ever happens after warmup (asserted in
tests/test_serve.py via the jit cache size).

Greedy decode through the engine is token-identical to
``GenerateMixin.generate`` (same prefill/decode closures, same argmax),
which anchors the whole subsystem's correctness to existing behavior.

The engine loop is guarded by ``utils.failure.Heartbeat`` when
``heartbeat_timeout_s`` is set: a hung device dispatch surfaces as a
clean abort instead of wedging the server.
"""

from __future__ import annotations

import time
from contextlib import nullcontext
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models._generate import _bound, decode_step, prefill_step
from ..obs import events
from ..utils.failure import Heartbeat
from .metrics import ServeMetrics
from .scheduler import (EVICTED, FINISHED, RUNNING, QueueFull, Request,
                        RequestHandle, Scheduler)
from .slots import SlotPool

__all__ = ["ServeEngine", "QueueFull"]


class ServeEngine:
    """Continuous-batching engine over one decoder model.

        eng = ServeEngine(model, num_slots=8, max_len=256)
        h = eng.submit(prompt_ids, max_new_tokens=64, deadline_s=30.0)
        eng.run_until_idle()
        full = h.result()              # prompt + generated tokens

    ``step()`` advances the whole arena by one decode tick (evict →
    admit/prefill → decode), delivering one token to every live request
    and invoking their streaming ``on_token`` callbacks.

    Decoding is greedy — the serving counterpart of
    ``generate(temperature=0)`` and token-identical to it.
    """

    def __init__(self, model, num_slots: int, max_len: int, *,
                 prefill_len: Optional[int] = None,
                 max_queue: Optional[int] = None,
                 param_dtype=None,
                 heartbeat_timeout_s: Optional[float] = None,
                 on_failure=None):
        self.model = model
        self.prefill_len = int(prefill_len or max_len - 1)
        if not 0 < self.prefill_len < max_len:
            raise ValueError(
                f"prefill_len must be in (0, max_len), got "
                f"{self.prefill_len} for max_len {max_len}")
        max_pos = getattr(getattr(model, "cfg", None), "max_position", None)
        if max_pos is not None and max_len > max_pos:
            raise ValueError(
                f"max_len ({max_len}) exceeds the model's max_position "
                f"({max_pos})")
        self.sched = Scheduler(
            max_queue=2 * num_slots if max_queue is None else max_queue)
        self.metrics = ServeMetrics()
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self._on_failure = on_failure

        # weights snapshotted once (same pattern as _gen_setup); decode
        # is weight-read bound, so an optional one-time bf16 cast halves
        # per-token HBM traffic on TPU
        params = {n: t.data for n, t in model.get_params().items()}
        if not params:
            raise ValueError(
                "model has no initialized params — call model.compile() "
                "(or run one forward) before building a ServeEngine")
        buffers = {n: t.data for n, t in model._get_buffers().items()}
        arena_dtype = None
        if param_dtype is not None:
            params = {n: (a.astype(param_dtype)
                          if jnp.issubdtype(a.dtype, jnp.floating) else a)
                      for n, a in params.items()}
            # the arena must match the dtype init_caches picks under the
            # CAST params inside the prefill trace (models size their
            # caches off the bound weights' dtype) — otherwise the
            # fresh-row splice type-mismatches at trace time.  eval_shape
            # under the cast binding reads that dtype without allocating.
            with _bound(model, params, buffers):
                spec = jax.eval_shape(lambda: model.init_caches(1, 2))
            arena_dtype = jax.tree.leaves(spec)[0].dtype
        self._params, self._buffers = params, buffers
        self.pool = SlotPool(model, num_slots, max_len, dtype=arena_dtype)

        self._running: Dict[int, Request] = {}      # slot -> request
        # device-resident per-slot last tokens: written by prefill (the
        # request's first token) and decode (each next token); the host
        # only ever FETCHES this small int vector — tokens are never
        # uploaded, so the decode hot loop is one dispatch + one tiny
        # fetch per tick
        self._toks = jnp.zeros((num_slots,), jnp.int32)

        # ---- the exactly-two compiled programs --------------------------
        pf = prefill_step(model, max_len, last_only=False)

        def prefill_into_slot(params, buffers, ids, length, slot, toks,
                              caches):
            logits, fresh = pf(params, buffers, ids)
            last = jax.lax.dynamic_slice_in_dim(
                logits, length - 1, 1, axis=1)[:, 0, :]
            # greedy pick in-program (jnp.argmax — bit-identical to
            # _pick_impl's temperature-0 branch in generate())
            tok = jnp.argmax(last, axis=-1).astype(jnp.int32)[0]
            toks = toks.at[slot].set(tok)
            new = [
                (jax.lax.dynamic_update_slice_in_dim(ak, fk, slot, axis=0),
                 jax.lax.dynamic_update_slice_in_dim(av, fv, slot, axis=0))
                for (ak, av), (fk, fv) in zip(caches, fresh)]
            return toks, new

        dec = decode_step(model)

        def decode_over_slots(params, buffers, toks, pos, active, caches):
            # inactive slots are masked: position clamped to 0 (their
            # stale cache row is overwritten wholesale by the next
            # prefill, so the position-0 scribble is harmless and keeps
            # every row's attention window non-empty → no NaN softmax),
            # and their token entry frozen so nothing downstream reads a
            # garbage argmax
            posc = jnp.where(active, pos, 0)
            logits, caches = dec(params, buffers, toks[:, None], posc,
                                 caches)
            picked = jnp.argmax(logits.astype(jnp.float32),
                                axis=-1).astype(jnp.int32)
            new_toks = jnp.where(active, picked, toks)
            new_pos = jnp.where(active, pos + 1, pos)
            return new_toks, new_pos, caches

        self._prefill = jax.jit(prefill_into_slot, donate_argnums=(6,))
        self._decode = jax.jit(decode_over_slots, donate_argnums=(5,))

    # -- introspection ----------------------------------------------------
    def compiled_counts(self):
        """(prefill, decode) jit-cache entry counts — the no-recompile
        invariant says both stay at 1 after warmup (tested)."""
        return (self._prefill._cache_size(), self._decode._cache_size())

    @property
    def pending(self) -> int:
        """Requests still in flight (queued + running)."""
        return self.sched.depth + len(self._running)

    # -- submission --------------------------------------------------------
    def submit(self, prompt_ids, *, max_new_tokens: int,
               deadline_s: Optional[float] = None,
               eos_id: Optional[int] = None,
               on_token=None) -> RequestHandle:
        """Queue one generation request; returns its handle.

        Raises :class:`QueueFull` when admission control refuses the
        request — the wait queue is at capacity.  Admission out of the
        queue into slots happens only at ``step()`` boundaries, so a
        burst of more than ``max_queue`` un-stepped submissions is
        rejected even while slots are free (size ``max_queue`` for the
        largest burst to absorb; default ``2 * num_slots``).  Raises
        ``ValueError`` when the request cannot ever fit the arena
        (prompt longer than ``prefill_len``, or prompt + budget past
        ``max_len`` — the arena guarantee that decode never writes out
        of bounds is enforced here, at the door)."""
        req = Request(prompt_ids, max_new_tokens, deadline_s, eos_id,
                      on_token)
        p = req.prompt.size
        if p > self.prefill_len:
            raise ValueError(
                f"prompt ({p} tokens) exceeds prefill_len "
                f"({self.prefill_len})")
        if p + req.max_new_tokens > self.pool.max_len:
            raise ValueError(
                f"prompt ({p}) + max_new_tokens ({req.max_new_tokens}) "
                f"= {p + req.max_new_tokens} exceeds max_len "
                f"({self.pool.max_len})")
        try:
            self.sched.offer(req)
        except QueueFull:
            self.metrics.on_reject()
            raise
        self.metrics.on_submit()
        return req.handle

    # -- the engine loop ---------------------------------------------------
    def step(self) -> int:
        """One continuous-batching tick: deadline eviction → admission
        (prefill queued requests into free slots) → one decode over all
        active slots.  Returns the number of tokens delivered."""
        with events.span("serve.step"):
            now = time.monotonic()
            delivered = 0

            # 1. deadline eviction — queued requests that died waiting
            #    and running requests past their deadline vacate first,
            #    so their slots are admittable this same tick
            for req in self.sched.expire_queued(now):
                self.metrics.on_evict("deadline")
            for slot in [s for s, r in self._running.items()
                         if r.expired(now)]:
                req = self._running[slot]
                req.finish_reason = "deadline"
                self._finalize(slot, evicted=True)

            # 2. admission — prefill into free slots between decode steps
            while self.pool.free_count:
                req = self.sched.pop_for_admission()
                if req is None:
                    break
                delivered += self._admit(req)

            # 3. one decode tick over the whole arena
            if self._running:
                delivered += self._decode_tick()

            self.metrics.on_step(self.sched.depth, self.pool.active_count)
        return delivered

    def run_until_idle(self, max_steps: Optional[int] = None) -> None:
        """Drive ``step()`` until no request is queued or running.  With
        ``heartbeat_timeout_s`` set, a Heartbeat watchdog guards every
        tick — a hung decode (dead device, wedged tunnel) aborts cleanly
        instead of wedging the server."""
        hb = Heartbeat(timeout=self.heartbeat_timeout_s,
                       on_failure=self._on_failure) \
            if self.heartbeat_timeout_s else None
        n = 0
        with hb if hb is not None else nullcontext():
            while self.pending:
                self.step()
                n += 1
                if hb is not None:
                    hb.beat(n)
                if max_steps is not None and n >= max_steps:
                    break

    # -- internals ---------------------------------------------------------
    def _admit(self, req: Request) -> int:
        slot = self.pool.alloc()
        assert slot is not None, "admission with no free slot"
        P = req.prompt.size
        ids = np.zeros((1, self.prefill_len), np.int32)
        ids[0, :P] = req.prompt
        with events.span("serve.prefill", slot=slot, prompt=P):
            self._toks, self.pool.caches = self._prefill(
                self._params, self._buffers, jnp.asarray(ids),
                jnp.asarray(P, jnp.int32), jnp.asarray(slot, jnp.int32),
                self._toks, self.pool.caches)
            tok = int(np.asarray(self._toks)[slot])
        self.pool.activate(slot, P)
        req.slot = slot
        req.state = RUNNING
        self._running[slot] = req
        self.metrics.on_admit()
        done = req.deliver(tok)       # prefill yields the first token
        self.metrics.on_first_token(req.ttft_s)
        if req.on_token is not None:
            req.on_token(tok, req.handle)
        if done:
            self._finalize(slot)
        return 1

    def _decode_tick(self) -> int:
        t0 = time.perf_counter()
        with events.span("serve.decode", active=len(self._running)):
            self._toks, new_pos, self.pool.caches = self._decode(
                self._params, self._buffers, self._toks,
                self.pool.pos, self.pool.active, self.pool.caches)
            toks = np.asarray(self._toks)    # tiny fetch: num_slots ints
        self.pool.pos = new_pos
        dt = time.perf_counter() - t0
        delivered = 0
        for slot in list(self._running):
            req = self._running[slot]
            tok = int(toks[slot])
            done = req.deliver(tok)
            self.metrics.on_token(dt)
            if req.on_token is not None:
                req.on_token(tok, req.handle)
            delivered += 1
            if done:
                self._finalize(slot)
        return delivered

    def _finalize(self, slot: int, evicted: bool = False) -> None:
        req = self._running.pop(slot)
        self.pool.release(slot)
        req.state = EVICTED if evicted else FINISHED
        self.metrics.on_evict(req.finish_reason or "unknown")
