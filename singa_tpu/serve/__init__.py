"""singa_tpu.serve — continuous-batching inference engine (ISSUE 2).

The serving counterpart of the Graph/Scheduler training layer: the
whole serving lifetime runs through exactly two compiled XLA programs.

* :mod:`~singa_tpu.serve.slots` — :class:`BlockPool`, the PAGED
  KV-cache arena built on ``ops/kv_cache``: fixed-size blocks behind
  per-request device-resident block tables, chain-hashed prefix-cache
  sharing with refcounts, and an evictable LRU of resident prefixes.
  Admit/evict/grow are pure index updates, freed blocks are reused
  without recompilation.  (The PR 2 fixed-slot ``SlotPool`` is gone —
  a default-sized ``BlockPool`` has capacity parity with it.)
* :mod:`~singa_tpu.serve.scheduler` — FIFO queue, admission control
  (:class:`QueueFull` backpressure), per-request deadlines and token
  budgets, eviction policy.
* :mod:`~singa_tpu.serve.engine` — :class:`ServeEngine`:
  ``submit() / step() / run_until_idle() / drain() / close()``,
  streaming token callbacks, greedy decode token-identical to
  ``GenerateMixin.generate``; resilience (ISSUE 4): bounded-backoff
  retry of transient dispatch failures, quarantine of requests that
  repeatedly poison prefill (a ``failed`` handle status, not an engine
  crash), deadline-aware overload shedding, and a Heartbeat-driven
  arena-recovery path (see docs/robustness.md).
* :mod:`~singa_tpu.serve.metrics` — queue/slot gauges, admit/reject/
  evict counters, TTFT and per-token latency histograms through
  ``obs.events``.
* :mod:`~singa_tpu.serve.spec` — speculative decoding (ISSUE 13):
  draft-model propose-k / target-model verify-k as a third compiled
  program over the same paged arena (the draft's KV blocks ride the
  same block tables); accepted runs are the target's own greedy picks
  (bitwise identical to ``generate()`` by construction), rejected
  positions roll back by position/limit truncation, and an injected
  ``serve.verify`` fault falls back to plain decode for that tick.
* :mod:`~singa_tpu.serve.disagg` — disaggregated serving (ISSUE 12):
  separately scaled prefill/decode worker pools (engines sharing ONE
  set of compiled programs) behind an SLO-aware :class:`Router` with
  per-tenant quotas, KV block handoff between arenas, and worker-death
  re-routing with bitwise-identical streams.
* :mod:`~singa_tpu.serve.net` — multi-process disaggregated serving
  (ISSUE 18): the same tier with each worker a ``ServeEngine`` in its
  own OS process behind a framed local-socket RPC, KV handoff over a
  versioned digest-checked wire codec (a torn transfer is never
  injected — it replays), and elastic grow/shrink of either pool at
  runtime (:class:`ProcRouter` / :func:`build_proc_pools` /
  :class:`ElasticPolicy`).

See docs/serving.md for the architecture, the slot lifecycle and the
backpressure semantics.
"""

from .disagg import (QuotaExceeded, Router, SLOClass, Worker,
                     build_pools)
from .engine import EngineClosed, ServeEngine, SharedPrograms
from .net import (ElasticPolicy, ProcHandle, ProcRouter, WorkerDied,
                  WorkerProc, build_proc_pools)
from .scheduler import (EVICTED, FAILED, FINISHED, QUEUED, RUNNING,
                        QueueFull, RequestHandle, Scheduler)
from .slots import BlockPool

__all__ = ["ServeEngine", "BlockPool", "Scheduler", "RequestHandle",
           "QueueFull", "EngineClosed", "SharedPrograms",
           "Router", "SLOClass", "QuotaExceeded", "Worker",
           "build_pools",
           "ProcRouter", "ProcHandle", "WorkerProc", "WorkerDied",
           "build_proc_pools", "ElasticPolicy",
           "QUEUED", "RUNNING", "FINISHED", "EVICTED", "FAILED"]
