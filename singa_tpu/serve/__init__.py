"""singa_tpu.serve — continuous-batching inference engine (ISSUE 2).

The serving counterpart of the Graph/Scheduler training layer: the
whole serving lifetime runs through exactly two compiled XLA programs.

* :mod:`~singa_tpu.serve.slots` — :class:`SlotPool`, the fixed
  (num_slots, max_len) KV-cache arena built on ``ops/kv_cache``;
  admit/evict are pure index updates, freed slots are reused without
  recompilation.
* :mod:`~singa_tpu.serve.scheduler` — FIFO queue, admission control
  (:class:`QueueFull` backpressure), per-request deadlines and token
  budgets, eviction policy.
* :mod:`~singa_tpu.serve.engine` — :class:`ServeEngine`:
  ``submit() / step() / run_until_idle()``, streaming token callbacks,
  greedy decode token-identical to ``GenerateMixin.generate``.
* :mod:`~singa_tpu.serve.metrics` — queue/slot gauges, admit/reject/
  evict counters, TTFT and per-token latency histograms through
  ``obs.events``.

See docs/serving.md for the architecture, the slot lifecycle and the
backpressure semantics.
"""

from .engine import ServeEngine
from .scheduler import QueueFull, RequestHandle, Scheduler
from .slots import SlotPool

__all__ = ["ServeEngine", "SlotPool", "Scheduler", "RequestHandle",
           "QueueFull"]
