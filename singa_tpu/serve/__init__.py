"""singa_tpu.serve — continuous-batching inference engine (ISSUE 2).

The serving counterpart of the Graph/Scheduler training layer: the
whole serving lifetime runs through exactly two compiled XLA programs.

* :mod:`~singa_tpu.serve.slots` — :class:`SlotPool`, the fixed
  (num_slots, max_len) KV-cache arena built on ``ops/kv_cache``;
  admit/evict are pure index updates, freed slots are reused without
  recompilation.
* :mod:`~singa_tpu.serve.scheduler` — FIFO queue, admission control
  (:class:`QueueFull` backpressure), per-request deadlines and token
  budgets, eviction policy.
* :mod:`~singa_tpu.serve.engine` — :class:`ServeEngine`:
  ``submit() / step() / run_until_idle() / drain() / close()``,
  streaming token callbacks, greedy decode token-identical to
  ``GenerateMixin.generate``; resilience (ISSUE 4): bounded-backoff
  retry of transient dispatch failures, quarantine of requests that
  repeatedly poison prefill (a ``failed`` handle status, not an engine
  crash), deadline-aware overload shedding, and a Heartbeat-driven
  arena-recovery path (see docs/robustness.md).
* :mod:`~singa_tpu.serve.metrics` — queue/slot gauges, admit/reject/
  evict counters, TTFT and per-token latency histograms through
  ``obs.events``.

See docs/serving.md for the architecture, the slot lifecycle and the
backpressure semantics.
"""

from .engine import EngineClosed, ServeEngine
from .scheduler import (EVICTED, FAILED, FINISHED, QUEUED, RUNNING,
                        QueueFull, RequestHandle, Scheduler)
from .slots import SlotPool

__all__ = ["ServeEngine", "SlotPool", "Scheduler", "RequestHandle",
           "QueueFull", "EngineClosed",
           "QUEUED", "RUNNING", "FINISHED", "EVICTED", "FAILED"]
