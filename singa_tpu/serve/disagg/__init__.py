"""singa_tpu.serve.disagg — disaggregated serving (ISSUE 12).

Prefill and decode live in opposite roofline classes (prefill
compute-bound, decode memory-bound — hlocost's committed baselines),
so one engine co-scheduling both wastes whichever resource the traffic
mix doesn't saturate.  This package splits them into separately scaled
pools behind an SLO-aware front door:

* :mod:`~singa_tpu.serve.disagg.worker` — :class:`Worker` (one
  :class:`~singa_tpu.serve.engine.ServeEngine` + a ``prefill`` /
  ``decode`` role) and :func:`build_pools`, which constructs N + M
  same-config workers sharing ONE set of compiled programs
  (``SharedPrograms``) — a whole tier costs one engine's compiles and
  the per-worker two-program invariant is asserted on the shared
  caches.
* :mod:`~singa_tpu.serve.disagg.handoff` — the KV block handoff: a
  finished prefill is just blocks + a table row, gathered through the
  engine's optional third compiled program (``handoff_gather``) and
  scattered into the destination pool block-by-block; refcounts and
  prefix-cache chain keys transfer with the blocks, so shared prefixes
  cross once per decode worker, not once per request.
* :mod:`~singa_tpu.serve.disagg.router` — :class:`Router`:
  per-tenant quotas, :class:`SLOClass` deadlines enforced by the
  existing scheduler backpressure/shed machinery, least-loaded
  routing, the ``serve.handoff``/``serve.router`` fault sites
  (worker death → re-route, re-prefill from prompt, streams bitwise
  identical), and one trace id per request across every worker it
  touches (``tools/obsq trace``).

``tools/loadgen.py --prefill-workers N --decode-workers M
[--ratio-sweep N:M,...]`` drives the tier open-loop and commits
``serve_load`` records with the per-pool fields; see
docs/serving.md ("Disaggregated tier").
"""

from .handoff import HandoffPackage
from .router import QuotaExceeded, Router, SLOClass, TierMetrics
from .worker import DECODE, PREFILL, Worker, build_pools

__all__ = ["Router", "SLOClass", "QuotaExceeded", "TierMetrics",
           "Worker", "build_pools", "HandoffPackage",
           "PREFILL", "DECODE"]
