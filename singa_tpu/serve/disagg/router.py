"""SLO-aware front door of the disaggregated serving tier (ISSUE 12).

The :class:`Router` owns N prefill workers and M decode workers
(:mod:`~singa_tpu.serve.disagg.worker`) and drives the whole tier from
one host loop:

* **submit** — resolves the request's SLO class to a deadline, applies
  per-tenant quotas, and offers the request to the least-loaded alive
  prefill worker.  Admission IS the existing ``Scheduler`` machinery:
  a full worker queue raises :class:`~singa_tpu.serve.scheduler.
  QueueFull` (the router tries the next worker, then rejects), queued
  requests past their deadline are evicted, and overload is shed by
  ``shed_overload`` — whose eta now runs against the ROUTER's round
  cadence (``ServeEngine.tick_hint_s``), because a worker stepped once
  per round would otherwise under-estimate queue wait by
  (round / own tick) and admit doomed requests.
* **step** — one tier round: every prefill worker ticks with
  ``step(decode=False)`` (admission only), finished prefills are
  handed off to the least-loaded decode worker with capacity
  (:mod:`~singa_tpu.serve.disagg.handoff` — refcounts and prefix keys
  transfer with the blocks), then every decode worker ticks.  A
  handoff the decode pool cannot absorb stays parked in its prefill
  slot (deadline eviction still guards it) — that back-pressure is the
  signal the decode pool is the bottleneck.
* **resilience** — a worker whose ``step()`` raises past the engine's
  own retry/recovery budget (or is killed via :meth:`Router.
  kill_worker`) is marked dead: its flight ring is dumped, an
  ``incident`` record with a ``flight_ref`` lands in the store, and
  every request the router had placed on it re-prefills from prompt +
  tokens-so-far on the surviving prefill pool — greedy replay makes
  the streams bitwise identical to a fault-free run.  The
  ``serve.handoff`` fault site models a worker dying MID-handoff: the
  in-flight KV is treated as lost and the request re-routes the same
  way.  Degraded modes rather than wedges: with the whole decode pool
  dead, prefill workers decode locally (co-located fallback); with the
  prefill pool dead, submits route to decode workers (every engine
  keeps both programs).
* **observability** — the router assigns each request ONE trace id
  (``<tier run_id>/q<n>``) that rides through every worker it touches,
  so ``python -m tools.obsq trace <id>`` renders the full cross-worker
  timeline: ``serve.route`` (worker choice) → ``serve.submitted`` →
  prefill spans → ``serve.handoff`` span (src/dst) → decode
  ``serve.token`` deliveries → ``serve.evicted`` (finish).  Tier-level
  metrics: ``serve.handoffs`` counter, ``serve.handoff_ms`` histogram
  (prefill-finish → decode-inject, queueing included),
  ``serve.rerouted`` counter, ``serve.worker_dead`` counter.

Why the split pays: hlocost's committed baselines class prefill
compute-bound and decode memory-bound, so the pools scale against
DIFFERENT bottlenecks — shifting the N:M ratio under the same offered
load moves TTFT p99 (prefill queueing) and tokens/s (decode slots) in
opposite directions, which ``tools/loadgen.py --ratio-sweep`` measures
and commits as ``serve_load`` records.
"""

from __future__ import annotations

import itertools
import time
import warnings
from typing import Dict, List, Optional, Tuple, Union

from ... import faults
from ...obs import events
from ...obs import flight as obs_flight
from ...obs import record as obs_record
from ...obs import trace as obs_trace
from ...obs.events import _Hist
from ...utils import failure
from ..engine import EngineClosed
from ..scheduler import QUEUED, QueueFull, Request, RequestHandle
from .handoff import HandoffPackage
from .worker import Worker

__all__ = ["Router", "SLOClass", "QuotaExceeded", "TierMetrics"]


class QuotaExceeded(QueueFull):
    """Admission refused at the tier door: the tenant is at its
    in-flight quota.  A subclass of :class:`QueueFull` so open-loop
    drivers (tools/loadgen.py) count it as the overload outcome it
    is."""


class SLOClass:
    """One named service level: requests submitted under it inherit
    its deadline (seconds; None = no deadline, the batch class), which
    the existing deadline-eviction + shed machinery then enforces —
    SLO classes are POLICY over the scheduler, not new mechanism."""

    def __init__(self, name: str, deadline_s: Optional[float]):
        self.name = str(name)
        if deadline_s is not None and float(deadline_s) <= 0:
            raise ValueError(
                f"SLO class {name!r}: deadline_s must be positive or "
                f"None, got {deadline_s}")
        self.deadline_s = None if deadline_s is None else float(deadline_s)

    def __repr__(self) -> str:
        return f"SLOClass({self.name!r}, deadline_s={self.deadline_s})"


def _merged_summary(hists: List[_Hist]) -> Optional[dict]:
    """Percentile summary across per-worker histograms: exact while
    every worker's observation count fits its sample ring (loadgen-
    scale runs), nearest-rank over the merged recent windows beyond."""
    m = _Hist()
    for h in hists:
        for v in h.samples:
            m.observe(v)
    return m.summary()


class TierMetrics:
    """Tier-wide view: the router's own counters (handoffs, reroutes,
    quota/door rejections, worker deaths) plus aggregation over every
    worker's :class:`~singa_tpu.serve.metrics.ServeMetrics` — so
    ``snapshot()`` has the same shape a single engine's does (what
    ``tools/loadgen.py`` consumes) with the tier extras on top."""

    def __init__(self, router: "Router"):
        self._router = router
        self.handoffs = 0
        self.reroutes = 0
        self.quota_rejected = 0
        self.door_rejected = 0
        self.worker_deaths = 0
        self.steps = 0
        self._handoff = _Hist()

    # -- router-side events ------------------------------------------------
    def on_handoff(self, wait_ms: float) -> None:
        self.handoffs += 1
        self._handoff.observe(wait_ms)
        events.counter("serve.handoffs", 1)
        events.histogram("serve.handoff_ms", wait_ms)

    def on_reroute(self) -> None:
        self.reroutes += 1
        events.counter("serve.rerouted", 1)

    def on_quota_reject(self, tenant: str) -> None:
        self.quota_rejected += 1
        events.counter("serve.rejected", 1, reason="quota",
                       tenant=tenant)

    def on_door_reject(self) -> None:
        self.door_rejected += 1
        events.counter("serve.rejected", 1, reason="tier_full")

    def on_worker_death(self, worker: str) -> None:
        self.worker_deaths += 1
        events.counter("serve.worker_dead", 1, worker=worker)

    def on_step(self) -> None:
        self.steps += 1

    def handoff_summary(self) -> Optional[dict]:
        return self._handoff.summary()

    # -- tier aggregation --------------------------------------------------
    def snapshot(self) -> dict:
        workers = self._router.prefill + self._router.decode
        snaps = [w.engine.metrics.snapshot() for w in workers]

        def total(key: str) -> int:
            return sum(s[key] for s in snaps)

        def merge(key: str) -> Dict[str, int]:
            out: Dict[str, int] = {}
            for s in snaps:
                for k, v in s[key].items():
                    out[k] = out.get(k, 0) + v
            return out

        spec_proposed = total("spec_proposed")
        disp = sum(s["slot_dispatches"] for s in snaps)
        disp_tokens = sum(s["slot_dispatch_tokens"] for s in snaps)
        return {
            "submitted": total("submitted"),
            # speculative decoding (ISSUE 13): tier-wide accept/dispatch
            # accounting — decode workers carry the draft, so the tier
            # headline aggregates their verify rounds
            "spec_rounds": total("spec_rounds"),
            "spec_proposed": spec_proposed,
            "spec_accepted": total("spec_accepted"),
            "spec_fallbacks": total("spec_fallbacks"),
            "accept_rate": (total("spec_accepted") / spec_proposed
                            if spec_proposed else None),
            "tokens_per_dispatch": (disp_tokens / disp if disp else None),
            "admitted": total("admitted"),
            # rejections are counted at the TIER door only: a worker's
            # own rejected counter ticks on every QueueFull the router
            # absorbs while trying the next worker, so summing those
            # would count one refused request once per attempted worker
            "rejected": self.quota_rejected + self.door_rejected,
            "evicted": merge("evicted"),
            "retries": merge("retries"),
            "quarantined": total("quarantined"),
            "recoveries": total("recoveries"),
            "preempted": total("preempted"),
            "prefix_hits": total("prefix_hits"),
            "prefix_hit_tokens": total("prefix_hit_tokens"),
            "steps": self.steps,
            "ttft_ms": _merged_summary(
                [w.engine.metrics._ttft for w in workers]),
            "token_ms": _merged_summary(
                [w.engine.metrics._token for w in workers]),
            "handoffs": self.handoffs,
            "handoff_ms": self.handoff_summary(),
            "reroutes": self.reroutes,
            "worker_deaths": self.worker_deaths,
        }


class Router:
    """Front door + tick loop of a prefill/decode worker tier; see the
    module docstring for the architecture.

        pw, dw = build_pools(model, 3, 1, num_slots=4, max_len=64)
        tier = Router(pw, dw,
                      slo_classes={"interactive": SLOClass("interactive",
                                                           5.0)},
                      tenant_quota=8)
        h = tier.submit(prompt, max_new_tokens=32, tenant="acme",
                        slo="interactive")
        tier.run_until_idle()
    """

    def __init__(self, prefill_workers: List[Worker],
                 decode_workers: List[Worker], *,
                 slo_classes: Optional[Dict[str, SLOClass]] = None,
                 tenant_quota: Union[None, int, Dict[str, int]] = None,
                 record_store: Optional[str] = None,
                 run_id: Optional[str] = None):
        self.prefill = list(prefill_workers)
        self.decode = list(decode_workers)
        if not self.prefill or not self.decode:
            raise ValueError("a tier needs at least one prefill and one "
                             "decode worker")
        names = [w.name for w in self.prefill + self.decode]
        if len(set(names)) != len(names):
            raise ValueError(f"worker names must be unique, got {names}")
        self.slo_classes = dict(slo_classes or {})
        for name, cls in self.slo_classes.items():
            if not isinstance(cls, SLOClass):
                raise ValueError(f"slo_classes[{name!r}] must be an "
                                 f"SLOClass, got {type(cls).__name__}")
        self.tenant_quota = tenant_quota
        self.record_store = record_store
        self.run_id = run_id or obs_record.new_run_id("tier")
        self.metrics = TierMetrics(self)
        self._seq = itertools.count()
        self._incident_seq = itertools.count()
        # the router's own host-side mirror of where every live request
        # is — worker death re-routes from HERE, never by reaching into
        # a dead engine (in a real deployment the dead worker's state
        # is simply gone; the mirror is what survives)
        self._handles: Dict[int, Tuple[RequestHandle,
                                       Optional[str]]] = {}
        self._where: Dict[int, Worker] = {}
        self._ready_at: Dict[int, float] = {}   # rid -> prefill-done t
        self._tick_ewma: Optional[float] = None
        self._draining = False
        self._closed = False

    # -- introspection -----------------------------------------------------
    @property
    def pending(self) -> int:
        """Requests queued or running anywhere in the tier (dead
        workers excluded — their requests were re-routed)."""
        return sum(w.engine.pending
                   for w in self.prefill + self.decode if w.alive)

    def worker(self, name: str) -> Worker:
        for w in self.prefill + self.decode:
            if w.name == name:
                return w
        raise KeyError(f"no worker named {name!r} "
                       f"(have: {[w.name for w in self.prefill + self.decode]})")

    def tier_stats(self) -> dict:
        """The per-pool ``serve_load`` record fields (obs/schema.py
        ``_SERVE_TIER_FIELDS``) — what ``tools/loadgen.py`` merges into
        each ratio-sweep point's payload."""
        summ = self.metrics.handoff_summary() or {}
        return {
            "prefill_workers": len(self.prefill),
            "decode_workers": len(self.decode),
            "handoffs": self.metrics.handoffs,
            "handoff_p99_ms": round(summ.get("p99", 0.0), 3),
        }

    # -- submission --------------------------------------------------------
    def submit(self, prompt_ids, *, max_new_tokens: int,
               tenant: Optional[str] = None,
               slo: Optional[str] = None,
               deadline_s: Optional[float] = None,
               eos_id: Optional[int] = None,
               on_token=None) -> RequestHandle:
        """Admit one request into the tier.  ``slo`` names a registered
        :class:`SLOClass` (its deadline applies unless ``deadline_s``
        overrides); ``tenant`` is the quota key.  Raises
        :class:`QuotaExceeded` at the tenant quota, :class:`QueueFull`
        when every prefill worker's queue refuses (the scheduler's
        admission backpressure, surfaced through the tier door), and
        ``ValueError`` for an unregistered SLO class."""
        if self._closed:
            raise EngineClosed("submit() on a closed tier")
        if self._draining:
            raise EngineClosed("tier is draining — new submissions are "
                               "refused while in-flight requests complete")
        faults.fire("serve.router", tenant=tenant or "", slo=slo or "")
        if slo is not None:
            cls = self.slo_classes.get(slo)
            if cls is None:
                raise ValueError(
                    f"unknown SLO class {slo!r} (registered: "
                    f"{sorted(self.slo_classes)})")
            if deadline_s is None:
                deadline_s = cls.deadline_s
        if tenant is not None:
            quota = self._quota_for(tenant)
            if quota is not None and self._tenant_live(tenant) >= quota:
                self.metrics.on_quota_reject(tenant)
                raise QuotaExceeded(
                    f"tenant {tenant!r} is at its in-flight quota "
                    f"({quota}); request rejected")
        trace_id = f"{self.run_id}/q{next(self._seq)}"
        for w in self._route_order(self._prefill_pool()):
            try:
                h = w.engine.submit(prompt_ids,
                                    max_new_tokens=max_new_tokens,
                                    deadline_s=deadline_s, eos_id=eos_id,
                                    on_token=on_token, trace_id=trace_id)
            except QueueFull:
                continue
            with obs_trace.activate(trace_id):
                events.counter("serve.route", 1, worker=w.name,
                               role=w.role)
            self._handles[h.rid] = (h, tenant)
            self._where[h.rid] = w
            return h
        self.metrics.on_door_reject()
        raise QueueFull(
            "every prefill worker's queue is at capacity; request "
            "rejected — shed load, raise max_queue, or add workers")

    def _quota_for(self, tenant: str) -> Optional[int]:
        q = self.tenant_quota
        if q is None:
            return None
        if isinstance(q, dict):
            return q.get(tenant)
        return int(q)

    def _tenant_live(self, tenant: str) -> int:
        return sum(1 for h, t in self._handles.values()
                   if t == tenant and not h.done)

    def _prefill_pool(self) -> List[Worker]:
        """Workers that accept new prompts: the alive prefill pool, or
        (degraded: prefill pool gone) the alive decode pool — every
        engine keeps both compiled programs, so a collapsed tier keeps
        serving co-located instead of wedging."""
        alive = [w for w in self.prefill if w.alive]
        return alive or [w for w in self.decode if w.alive]

    @staticmethod
    def _route_order(pool: List[Worker]) -> List[Worker]:
        """Least-loaded first; name breaks ties so routing is
        deterministic for a given tier state."""
        return sorted(pool, key=lambda w: (w.load, w.name))

    # -- the tier round ----------------------------------------------------
    def step(self) -> int:
        """One tier round: prefill ticks → handoffs → decode ticks →
        cadence hint.  Returns tokens delivered across the tier."""
        if self._closed:
            raise EngineClosed("step() on a closed tier")
        t0 = time.monotonic()
        delivered = 0
        with events.span("serve.tier_step"):
            self._prune()
            decode_alive = [w for w in self.decode if w.alive]
            for w in [p for p in self.prefill if p.alive]:
                # degraded co-location: with the decode pool gone, the
                # prefill workers decode their own slots
                delivered += self._step_worker(w, decode=not decode_alive)
            self._drain_prefills()
            for w in decode_alive:
                if w.alive:
                    delivered += self._step_worker(w, decode=True)
            if not any(w.alive for w in self.prefill + self.decode) \
                    and self.pending:
                raise RuntimeError(
                    "every worker in the tier is dead; cannot serve "
                    "the remaining requests")
            dt = time.monotonic() - t0
            self._tick_ewma = dt if self._tick_ewma is None else \
                0.8 * self._tick_ewma + 0.2 * dt
            # the shed eta's admission cadence is the ROUTER round, not
            # one worker's own tick (scheduler.eta_first_token)
            for w in self.prefill + self.decode:
                w.engine.tick_hint_s = self._tick_ewma
            self.metrics.on_step()
        return delivered

    def _step_worker(self, w: Worker, decode: bool) -> int:
        try:
            return w.engine.step(decode=decode)
        except (RuntimeError, OSError) as e:
            if isinstance(e, failure.FailureDetected):
                raise
            # the engine exhausted its OWN retry/recovery budget — at
            # the tier level that is a worker death, not a crash
            self._worker_death(w, f"step: {type(e).__name__}: {e}")
            return 0

    def run_until_idle(self, max_steps: Optional[int] = None) -> None:
        n = 0
        while self.pending:
            self.step()
            n += 1
            if max_steps is not None and n >= max_steps:
                break

    def drain(self, max_steps: Optional[int] = None) -> None:
        """Refuse new submissions and complete everything in flight."""
        self._draining = True
        self.run_until_idle(max_steps=max_steps)

    def close(self) -> None:
        """Drain, then close every alive worker engine (dead workers'
        engines are abandoned — their requests were re-routed).
        Idempotent."""
        if self._closed:
            return
        self.drain()
        self._closed = True
        for w in self.prefill + self.decode:
            if w.alive:
                w.engine.close()

    def __enter__(self) -> "Router":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    # -- handoff -----------------------------------------------------------
    def _drain_prefills(self) -> None:
        """Move every finished prefill whose KV a decode worker can
        hold; the rest stay parked (their deadline still ticks)."""
        now = time.monotonic()
        decode_alive = [w for w in self.decode if w.alive]
        if not decode_alive:
            return
        for w in [p for p in self.prefill if p.alive]:
            for slot, req in w.engine.running_items():
                if req.rid not in self._ready_at:
                    self._ready_at[req.rid] = now
                probe = HandoffPackage(
                    req=req, kv=None, pos=0,
                    n_blocks=w.engine.pool.mapped_count(slot),
                    prompt_keys=w.engine._req_keys(req)[
                        :req.prompt.size // w.engine.pool.block_size])
                dst = next(
                    (d for d in self._route_order(decode_alive)
                     if d.engine.can_accept_handoff(probe)), None)
                if dst is None:
                    continue
                self._handoff(w, slot, req, dst)

    def _handoff(self, src: Worker, slot: int, req: Request,
                 dst: Worker) -> None:
        ready = self._ready_at.get(req.rid)
        wait_ms = 0.0 if ready is None else \
            (time.monotonic() - ready) * 1e3
        try:
            with obs_trace.activate(req.trace_id):
                with events.span("serve.handoff", src=src.name,
                                 dst=dst.name, rid=req.rid):
                    faults.fire("serve.handoff", rid=req.rid,
                                src=src.name, dst=dst.name)
                    pkg = src.engine.extract_handoff(slot)
                    ok = dst.engine.inject_handoff(pkg)
        except (RuntimeError, OSError) as e:
            if isinstance(e, failure.FailureDetected):
                raise
            self._reroute(req, src,
                          f"handoff {src.name}->{dst.name}: "
                          f"{type(e).__name__}: {e}")
            return
        if not ok:
            # capacity vanished between probe and inject (defensive —
            # the tier loop is single-threaded): replay from prompt
            self._requeue_prefill(req)
            return
        self._ready_at.pop(req.rid, None)
        self._where[req.rid] = dst
        self.metrics.on_handoff(wait_ms)

    # -- re-routing + worker death ----------------------------------------
    def _reroute(self, req: Request, src: Worker, reason: str) -> None:
        """A handoff died with the KV in flight: the blocks are treated
        as lost and the request re-prefills from prompt + tokens-so-far
        on the prefill pool — greedy replay keeps its stream bitwise
        identical (the same argument as preemption/recovery)."""
        self.metrics.on_reroute()
        if req.slot is not None and src.alive:
            # the fault fired before extraction — the request is still
            # occupying its source slot; release it
            src.engine.withdraw(req.slot)
        warnings.warn(f"disagg: re-routing request {req.rid} "
                      f"({reason}); it will re-prefill from prompt",
                      stacklevel=2)
        self._requeue_prefill(req)
        self._incident("serve.handoff", reason, f"req:{req.rid}",
                       "rerouted", 0,
                       flight_ref=self._flight_dump("serve.handoff", src,
                                                    reason))

    def _requeue_prefill(self, req: Request) -> None:
        self._ready_at.pop(req.rid, None)
        pool = self._prefill_pool()
        if not pool:
            raise RuntimeError(
                f"no alive worker to re-route request {req.rid} to")
        w = self._route_order(pool)[0]
        req.state = QUEUED
        req.slot = None
        # requeue_front: the request was already admitted once — it
        # keeps its FIFO priority and bypasses max_queue backpressure
        w.engine.sched.requeue_front([req])
        self._where[req.rid] = w

    def kill_worker(self, name: str, reason: str = "killed") -> None:
        """Operations/chaos hook: declare ``name`` dead now — its
        flight ring is dumped, an incident records the death, and every
        request the router had placed on it re-routes."""
        self._worker_death(self.worker(name), reason)

    def _worker_death(self, w: Worker, reason: str) -> None:
        if not w.alive:
            return
        w.alive = False
        self.metrics.on_worker_death(w.name)
        warnings.warn(f"disagg: worker {w.name} died ({reason}); "
                      f"re-routing its in-flight requests", stacklevel=2)
        # the dead worker's ring is the incident evidence: its last-N
        # events (prefill/decode/handoff notes) travel with the record
        ref = self._flight_dump("serve.router", w,
                                f"worker {w.name} death: {reason}")
        victims = []
        for rid, (h, _) in list(self._handles.items()):
            if self._where.get(rid) is w and not h.done:
                # same-package access: the handle's request IS the
                # router's host-side mirror of the lost worker state
                victims.append(h._req)
        # requeue_front prepends, so victims are re-queued NEWEST
        # first: after the loop the oldest rid sits at the head and
        # FIFO priority survives the death (two victims landing on the
        # same survivor keep their original order)
        for req in sorted(victims, key=lambda r: r.rid, reverse=True):
            self._requeue_prefill(req)
        self._incident("serve.router", "worker_death", w.name,
                       "rerouted", len(victims), flight_ref=ref)

    def _prune(self) -> None:
        """Drop finished requests from the mirror (bounded memory over
        long-lived tiers)."""
        for rid, (h, _) in list(self._handles.items()):
            if h.done:
                self._handles.pop(rid, None)
                self._where.pop(rid, None)
                self._ready_at.pop(rid, None)

    # -- durable incident records + flight dumps ---------------------------
    def _flight_dump(self, site: str, worker: Worker,
                     reason: str) -> Optional[str]:
        """Dump ``worker``'s flight ring next to the record store and
        return the ``flight_ref`` (None without a store) — the same
        :func:`obs.flight.dump_for_store` contract as the engine's;
        literal sites at call sites stay SGL009-checkable."""
        return obs_flight.dump_for_store(worker.engine.flight, site,
                                         self.record_store, reason)

    def _incident(self, site: str, fault: str, ref, outcome: str,
                  retries: int, flight_ref: Optional[str] = None) -> None:
        """Append one ``incident`` entry (mirrors
        ``ServeEngine._incident`` — best-effort, never a crash)."""
        events.counter("serve.incident", 1, site=site, outcome=outcome)
        if not self.record_store:
            return
        try:
            import jax
            platform = jax.default_backend()
            dev = jax.devices()[0]
            payload = {"site": site, "fault": fault, "ref": ref,
                       "outcome": outcome, "retries": int(retries),
                       "engine_run": self.run_id}
            if flight_ref:
                payload["flight_ref"] = flight_ref
            entry = obs_record.new_entry(
                "incident", platform, platform != "tpu",
                getattr(dev, "device_kind", "") or platform,
                run_id=f"{self.run_id}-inc{next(self._incident_seq)}",
                payload=payload)
            obs_record.RunRecord(self.record_store).append(entry)
        except Exception as e:
            warnings.warn(f"could not append incident record: "
                          f"{type(e).__name__}: {e}", stacklevel=2)
