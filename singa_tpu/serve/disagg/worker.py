"""Worker pools for the disaggregated serving tier (ISSUE 12).

A *worker* is one :class:`~singa_tpu.serve.engine.ServeEngine` plus its
role in the tier — ``"prefill"`` (ticked with ``step(decode=False)``,
its finished prefills handed off by the router) or ``"decode"``
(receives handoffs, runs plain decode ticks; its own queue is normally
empty, but the engine keeps BOTH compiled programs, so a decode-worker
arena recovery re-prefills locally without router involvement).

hlocost's committed baselines are the reason the split exists at all:
the prefill-chunk program is compute-bound and the decode program is
memory-bound (opposite roofline classes), so one engine co-scheduling
both wastes whichever resource the traffic mix doesn't saturate —
separately sized pools let each phase scale against ITS bottleneck.

:func:`build_pools` constructs N + M same-config workers that all
share ONE set of compiled programs (``SharedPrograms`` — jax caches by
callable + shapes, so homogeneous workers dispatching through shared
jitted callables never recompile): a whole tier costs exactly one
engine's compiles, and the per-worker two-program invariant is
literally the shared caches staying at one entry each (asserted in
tests/test_faults.py).

Speculative decoding (ISSUE 13): pass ``draft_model=``/``spec_k=``
through ``engine_kwargs`` and the WHOLE tier carries the draft —
prefill workers write both arenas (so a handoff package ships draft KV
alongside target KV, see handoff.py) and decode workers run verify-k
rounds.  ``SharedPrograms`` carries the verify executable, so a
homogeneous speculative tier still costs one engine's compiles.
"""

from __future__ import annotations

import itertools
from typing import List, Optional, Tuple

from ..engine import ServeEngine

__all__ = ["Worker", "build_pools", "PREFILL", "DECODE"]

PREFILL = "prefill"
DECODE = "decode"

_WORKER_SEQ = itertools.count()


class Worker:
    """One engine + its role.  ``alive`` is the router's health flag:
    a dead worker is never routed to again and its in-flight requests
    are re-routed (re-prefilled from prompt + tokens-so-far)."""

    def __init__(self, name: str, role: str, engine: ServeEngine):
        if role not in (PREFILL, DECODE):
            raise ValueError(f"unknown worker role {role!r} "
                             f"(expected {PREFILL!r} or {DECODE!r})")
        self.name = name
        self.role = role
        self.engine = engine
        self.alive = True

    @property
    def load(self) -> int:
        """Queued + running requests — the router's least-loaded
        routing key."""
        return self.engine.pending

    def __repr__(self) -> str:
        return (f"Worker({self.name!r}, {self.role}, "
                f"{'alive' if self.alive else 'DEAD'}, "
                f"load={self.load})")


def build_pools(model, n_prefill: int, n_decode: int, *,
                template: Optional[ServeEngine] = None,
                num_slots: int = 4, max_len: int = 64,
                block_size: int = 16,
                num_blocks: Optional[int] = None,
                share_prefix: bool = True,
                max_queue: Optional[int] = None,
                record_store: Optional[str] = None,
                **engine_kwargs) -> Tuple[List[Worker], List[Worker]]:
    """(prefill_workers, decode_workers): N + M homogeneous engines
    over ``model``, all sharing the compiled programs of ``template``
    (or of the first worker built here).  ``engine_kwargs`` pass
    through to every :class:`ServeEngine` (retry/backoff budgets,
    recovery limits, ...); ``record_store`` lands on each worker so
    per-worker incidents and flight dumps have a durable home."""
    if n_prefill < 1 or n_decode < 1:
        raise ValueError(
            f"a tier needs at least one worker per pool, got "
            f"{n_prefill} prefill / {n_decode} decode")
    kw = dict(block_size=block_size, num_blocks=num_blocks,
              share_prefix=share_prefix, max_queue=max_queue,
              record_store=record_store, **engine_kwargs)
    programs = template.programs() if template is not None else None
    gen = next(_WORKER_SEQ)
    prefill: List[Worker] = []
    decode: List[Worker] = []
    for pool, role, n in ((prefill, PREFILL, n_prefill),
                          (decode, DECODE, n_decode)):
        for i in range(n):
            eng = ServeEngine(model, num_slots, max_len,
                              programs=programs, **kw)
            if programs is None:
                programs = eng.programs()
            pool.append(Worker(f"{role[0]}{i}-{gen}", role, eng))
    return prefill, decode
