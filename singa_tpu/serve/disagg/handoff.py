"""KV block handoff between serving engines (ISSUE 12).

The paged arena makes a finished prefill cheap to move by
construction: it is just KV blocks plus a block-table row.  A handoff
therefore never reshapes a tensor —

* the SOURCE (a prefill worker) gathers the slot's dense per-layer
  view through its block-table row with ``ops.kv_cache.
  gather_block_kv`` — the engine's optional THIRD compiled program
  (``handoff_gather``, fixed shapes, lazily compiled on the first
  handoff, audited by the hloaudit/hlocost gates next to prefill and
  decode), then releases the slot;
* the DESTINATION (a decode worker) maps the same logical block
  sequence onto its own physical blocks: blocks whose prefix chain
  keys are already resident are matched COPY-FREE (``match_prefix`` —
  refcounts and prefix-cache keys transfer with the blocks, so a
  tenant's shared system prompt crosses the wire once per decode
  worker, not once per request), the rest are written with
  ``scatter_block_kv``, one fixed-shape block write per remaining
  logical block.

The request object itself (prompt, tokens-so-far, deadline, handle,
trace id) is pure host state and travels inside the
:class:`HandoffPackage`.  After injection the destination's decode
program continues the stream mid-flight: its per-slot position is the
replay length minus one and its last-token entry is the prefill's
first token, exactly the state a local prefill would have left —
which is why disaggregated greedy streams are bitwise identical to a
single engine's (asserted in tests/test_faults.py).

Correctness of copy-free matching rests on the same invariant the
prefix cache already stands on: a chain key commits to every token of
the whole prefix, and a full prompt block's KV content is a
deterministic function of those tokens under the shared weights, so a
key match means bitwise-equal block content no matter which worker
prefilled it.

These functions are the implementation behind
``ServeEngine.extract_handoff`` / ``inject_handoff`` /
``can_accept_handoff``; they reach into engine/pool internals by
design (same subsystem package).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import List, Optional

import jax.numpy as jnp

from ...obs import attr as obs_attr
from ...ops import kv_cache as kv_ops
from ..scheduler import RUNNING, Request


def _gather(engine, *args):
    """One ``handoff_gather`` dispatch, timed for the runtime-
    attribution ledger when one is installed (obs.attr — same
    zero-overhead-when-off seam as ``ServeEngine._dispatch``; the
    gather bypasses ``_dispatch`` because it must not retry: a retried
    gather after a partial failure could ship a torn package)."""
    led = obs_attr.get()
    if led is None:
        return engine._handoff(*args)
    t0 = time.perf_counter()
    out = engine._handoff(*args)
    led.note("handoff_gather", time.perf_counter() - t0)
    return out

__all__ = ["HandoffPackage", "extract", "inject", "can_accept"]


@dataclass
class HandoffPackage:
    """One prefilled request in flight between workers: the host-side
    request state plus its gathered KV and the prefix keys that let the
    destination map shared blocks copy-free."""

    req: Request
    #: per layer (dense_k, dense_v) device views, shape
    #: (1, max_blocks * block_size, K, D) — the handoff_gather output
    kv: list
    #: valid cache positions (== replay length - 1 == the per-slot
    #: ``pos`` the destination activates with)
    pos: int
    #: logical blocks the destination must map (shared + copied)
    n_blocks: int
    #: chain keys of the request's FULL prompt blocks ([] when the
    #: source pool has prefix sharing disabled) — what transfers the
    #: prefix-cache identity along with the block contents
    prompt_keys: List[bytes] = field(default_factory=list)
    #: source worker name (events/debugging only)
    src: str = ""
    #: speculative tier (ISSUE 13): the DRAFT model's dense per-layer
    #: views for the same blocks (None when the source engine carries
    #: no draft) — both arenas ride the same block tables, so the
    #: handoff moves both or the destination's verify rounds would
    #: start from a cold draft cache and accept nothing
    draft_kv: Optional[list] = None


def extract(engine, slot: int) -> HandoffPackage:
    """Pull the request in ``slot`` out of ``engine`` (see module
    docstring).  The gather runs BEFORE any bookkeeping mutation and
    the gather program does not donate, so a failure at any point
    leaves the source arena AND the engine's request map consistent —
    the request is still withdrawable for a re-route."""
    req = engine._running[slot]
    pool = engine.pool
    n_blocks = pool.mapped_count(slot)
    # device pos == replay length - 1 by construction (prefill
    # activates at the replay length then delivers one token; every
    # decode tick advances both) — no device fetch needed
    pos = req.replay_ids().size - 1
    if pool.draft_caches is not None:
        # speculative engine: ONE gather call over the combined
        # per-layer list (target caches + draft caches — a pytree, so
        # the handoff program still has exactly one jit-cache entry),
        # split back host-side
        both = _gather(engine, pool.tables, jnp.asarray(slot, jnp.int32),
                       pool.caches + pool.draft_caches)
        dense, draft_kv = both[:len(pool.caches)], both[len(pool.caches):]
    else:
        dense = _gather(engine, pool.tables,
                        jnp.asarray(slot, jnp.int32), pool.caches)
        draft_kv = None
    keys = engine._req_keys(req)[:req.prompt.size // pool.block_size]
    # point of no return: only after the gather succeeded
    engine._running.pop(slot)
    pool.release(slot)
    req.slot = None
    engine.flight.note("counter", "serve.handoff_out", rid=req.rid,
                       blocks=n_blocks)
    return HandoffPackage(req=req, kv=dense, pos=pos, n_blocks=n_blocks,
                          prompt_keys=keys, draft_kv=draft_kv)


def _probe(engine, pkg: HandoffPackage):
    """(n_shared, n_lru) of the destination's resident-prefix coverage
    for this package (side-effect free)."""
    if not engine.share_prefix or not pkg.prompt_keys:
        return 0, 0
    return engine.pool.probe_prefix(
        pkg.req.prompt, len(pkg.prompt_keys), keys=pkg.prompt_keys)


def can_accept(engine, pkg: HandoffPackage) -> bool:
    """Free slot + coverable blocks on ``engine`` for ``pkg``, counting
    resident shared-prefix blocks (claiming LRU-parked ones consumes
    availability, same accounting as admission)."""
    if engine.pool.free_count < 1:
        return False
    n_shared, n_lru = _probe(engine, pkg)
    return (engine.pool.available_blocks - n_lru
            >= pkg.n_blocks - n_shared)


def inject(engine, pkg: HandoffPackage) -> bool:
    """Install ``pkg`` into ``engine`` mid-stream (see module
    docstring).  Returns False when capacity is lacking — the caller
    parks the package; the destination is untouched."""
    if not can_accept(engine, pkg):
        return False
    req = pkg.req
    assert req.tokens, "handoff of a request with no prefill token"
    pool = engine.pool
    bs = pool.block_size
    n_shared = 0
    shared_ids: List[int] = []
    if engine.share_prefix and pkg.prompt_keys:
        n_shared, shared_ids = pool.match_prefix(
            req.prompt, len(pkg.prompt_keys), keys=pkg.prompt_keys)
    slot = pool.alloc_slot()
    owned = pool.alloc_blocks(pkg.n_blocks - n_shared) or []
    assert slot is not None and len(owned) == pkg.n_blocks - n_shared, \
        "capacity vanished between can_accept and inject"
    pool.map_slot(slot, shared_ids + owned)
    try:
        # copy only the unshared logical blocks out of the dense view —
        # one fixed-shape block scatter per (block, layer).  These are
        # EAGER ops: each write materializes a fresh arena buffer (no
        # donation outside jit) — the sanctioned cost of "no new jit
        # programs beyond the handoff gather" (ISSUE 12); on-chip, a
        # donating multi-block scatter program is the known upgrade
        # (ROADMAP item 3 note) if handoff copies ever show up in a
        # profile.
        caches = list(pool.caches)
        dcaches = (list(pool.draft_caches)
                   if pool.draft_caches is not None
                   and pkg.draft_kv is not None else None)
        for i, wb in enumerate(owned):
            lo = (n_shared + i) * bs
            for li, (dk, dv) in enumerate(pkg.kv):
                ck, cv = caches[li]
                caches[li] = kv_ops.scatter_block_kv(
                    ck, cv, jnp.asarray(wb, jnp.int32),
                    dk[0, lo:lo + bs], dv[0, lo:lo + bs])
            if dcaches is not None:
                # the draft arena maps the SAME physical block ids —
                # one more fixed-shape write per (block, draft layer)
                for li, (dk, dv) in enumerate(pkg.draft_kv):
                    ck, cv = dcaches[li]
                    dcaches[li] = kv_ops.scatter_block_kv(
                        ck, cv, jnp.asarray(wb, jnp.int32),
                        dk[0, lo:lo + bs], dv[0, lo:lo + bs])
        pool.caches = caches
        if dcaches is not None:
            pool.draft_caches = dcaches
        if engine.share_prefix and pkg.prompt_keys:
            pool.register_prefix(req.prompt, slot, len(pkg.prompt_keys),
                                 keys=pkg.prompt_keys)
        pool.activate(slot, pkg.pos)
        # decode reads the slot's LAST token as its next input
        engine._toks = engine._toks.at[slot].set(int(req.tokens[-1]))
    except BaseException:
        # unwind the claim so a mid-scatter failure cannot leak the
        # destination slot/blocks: release() drops the mapping (shared
        # keyed blocks park back in the LRU, owned unkeyed ones are
        # freed; partially-written content is unreachable garbage, the
        # same contract as any stale block).  The caller re-routes.
        pool.release(slot)
        raise
    req.slot = slot
    req.state = RUNNING
    engine._running[slot] = req
    engine.flight.note("counter", "serve.handoff_in", rid=req.rid,
                       blocks=pkg.n_blocks, shared=n_shared)
    return True
