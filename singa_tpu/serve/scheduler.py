"""Continuous-batching scheduler: request queue, admission control,
deadlines, eviction policy.

Pure host-side policy — no device code.  The engine asks the scheduler
three questions each step: who expired (deadline eviction, including
requests that died *waiting in the queue*), who to admit into the free
slots (FIFO — prefill interleaves between decode steps), and whether a
running request just finished (EOS / token budget / deadline).  Keeping
policy out of the engine keeps the two compiled programs policy-free:
scheduling decisions can change per step without touching XLA.

Admission control (backpressure): `offer()` refuses requests beyond
``max_queue`` waiting entries by raising :class:`QueueFull` — callers
see rejection at submit time, not a silently growing queue.
"""

from __future__ import annotations

import itertools
import time
from collections import deque
from typing import Callable, Deque, List, Optional

import numpy as np

__all__ = ["QueueFull", "Request", "RequestHandle", "Scheduler",
           "eta_first_token",
           "QUEUED", "RUNNING", "FINISHED", "EVICTED", "FAILED"]


def eta_first_token(position: int, *, free_slots: int, wave_size: int,
                    tick_s: float,
                    tokens_per_tick: float = 1.0) -> float:
    """Seconds until the queued request at ``position`` could plausibly
    deliver its first token — the ONE eta model behind
    :meth:`Scheduler.shed_overload` (engines and the disaggregated
    router's workers both delegate here).

    Shedding runs immediately before admission in the same tick, so the
    first ``free_slots`` queued requests prefill THIS tick — eta 0.0,
    never shed (a truly-expired deadline is eviction's job, not
    shedding's).  Requests behind that window wait about one admission
    period per wave of ``wave_size`` slots.

    ``tick_s`` is the ADMISSION PERIOD of the pool this queue drains
    into, not necessarily one engine's own step time: a worker stepped
    by the disaggregated Router gets one admission opportunity per
    ROUTER round (which steps every worker), so the router pushes its
    measured round time into each worker via
    ``ServeEngine.tick_hint_s`` and the eta uses the slower of the two
    clocks.  Before PR 12 the eta always used the engine's own
    tick EWMA, which under-estimated queue wait by (router round /
    engine tick) and let doomed requests through to burn prefills
    instead of being shed.

    ``tokens_per_tick`` is the measured ACCEPTED-tokens-per-tick per
    slot (``ServeEngine._tpt_ewma``): a speculative verify-k engine
    delivers up to ``k + 1`` tokens per dispatch, so its running slots
    free up proportionally faster and a queued request's wave count is
    worth ``tick_s / tokens_per_tick`` seconds, not ``tick_s``.
    Before ISSUE 13 the model hard-coded 1 token per tick, which
    over-estimated a spec engine's queue wait by that factor and shed
    requests that would have made their deadlines comfortably.  Values
    below 1 are clamped — a partially-delivered tick must not make the
    eta OPTIMISTIC about a plain engine."""
    if position < free_slots:
        return 0.0
    waves = 1 + (position - free_slots) // max(1, wave_size)
    return tick_s * waves / max(1.0, tokens_per_tick)

QUEUED = "queued"
RUNNING = "running"
FINISHED = "finished"
EVICTED = "evicted"
#: terminal state of a request the ENGINE gave up on (quarantined after
#: repeatedly poisoning prefill, or unrecoverable after an arena
#: rebuild) — surfaced on the handle instead of crashing the engine
FAILED = "failed"


class QueueFull(RuntimeError):
    """Admission refused: the wait queue is at capacity.  (Queued
    requests drain into slots only at step() boundaries, so a large
    enough burst between ticks is refused even while slots are free —
    bounded queueing is the backpressure contract.)  The caller should
    shed load or retry later."""


class Request:
    """One generation request's full lifecycle state (engine-internal;
    users hold the :class:`RequestHandle` view)."""

    _ids = itertools.count()

    def __init__(self, prompt_ids, max_new_tokens: int,
                 deadline_s: Optional[float],
                 eos_id: Optional[int],
                 on_token: Optional[Callable[[int, "RequestHandle"], None]]):
        self.rid = next(Request._ids)
        self.prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError("empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        self.submitted_at = time.monotonic()
        self.deadline = (self.submitted_at + float(deadline_s)
                         if deadline_s is not None else None)
        self.eos_id = eos_id
        self.on_token = on_token
        self.state = QUEUED
        self.slot: Optional[int] = None
        self.tokens: List[int] = []
        self.finish_reason: Optional[str] = None
        self.error: Optional[str] = None
        self.ttft_s: Optional[float] = None
        self._replay: Optional[np.ndarray] = None   # replay_ids memo
        self.prefix_keys: Optional[list] = None     # chain-key memo
        # stamped by ServeEngine.submit (engine run_id + rid): the
        # obs.trace id every event about this request carries
        self.trace_id: Optional[str] = None
        self.handle = RequestHandle(self)

    def replay_ids(self) -> np.ndarray:
        """prompt + tokens generated so far — what an arena-recovery
        re-prefill feeds the prefill program, and (via
        :meth:`RequestHandle.result`) the user-facing full sequence.
        Greedy decode makes the replay idempotent: the re-prefilled
        slot's next token is exactly the token decode would have
        produced next, so recovering any number of times leaves the
        final stream bit-identical.

        Memoized while the token list is unchanged: the admission path
        asks for the replay several times per step for a head-of-queue
        request waiting on free blocks (callers treat it read-only;
        the user-facing copy is :meth:`RequestHandle.result`)."""
        size = self.prompt.size + len(self.tokens)
        if self._replay is None or self._replay.size != size:
            self._replay = np.concatenate(
                [self.prompt, np.asarray(self.tokens, np.int32)])
        return self._replay

    # -- transitions (called by the engine) ------------------------------
    def deliver(self, tok: int) -> bool:
        """Record one generated token; returns True when the request is
        now complete (EOS emitted or token budget spent).  The EOS token
        itself is kept — same convention as GenerateMixin.generate."""
        if self.ttft_s is None:
            self.ttft_s = time.monotonic() - self.submitted_at
        self.tokens.append(int(tok))
        if self.eos_id is not None and int(tok) == self.eos_id:
            self.finish_reason = "eos"
            return True
        if len(self.tokens) >= self.max_new_tokens:
            self.finish_reason = "length"
            return True
        return False

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now > self.deadline


class RequestHandle:
    """User-facing view of a submitted request (returned by
    ``ServeEngine.submit``)."""

    def __init__(self, req: Request):
        self._req = req

    @property
    def rid(self) -> int:
        return self._req.rid

    @property
    def trace_id(self) -> Optional[str]:
        """The request's obs trace id (``<engine run_id>/r<rid>``) —
        the key for ``obsq trace`` and for slicing a flight-recorder
        dump to this request's timeline."""
        return self._req.trace_id

    @property
    def status(self) -> str:
        return self._req.state

    @property
    def done(self) -> bool:
        return self._req.state in (FINISHED, EVICTED, FAILED)

    @property
    def failed(self) -> bool:
        """True when the engine gave up on this request (quarantined /
        unrecoverable) — a per-request failure status, never an engine
        crash."""
        return self._req.state == FAILED

    @property
    def error(self) -> Optional[str]:
        """The failure message when :attr:`failed`, else None."""
        return self._req.error

    @property
    def finish_reason(self) -> Optional[str]:
        """'eos' | 'length' | 'deadline' | 'shed' | 'quarantined' |
        'unrecoverable' (None while in flight)."""
        return self._req.finish_reason

    @property
    def tokens(self) -> List[int]:
        """Generated token ids so far (no prompt)."""
        return list(self._req.tokens)

    @property
    def ttft_s(self) -> Optional[float]:
        return self._req.ttft_s

    def result(self) -> np.ndarray:
        """prompt + generated tokens as one int32 vector (a private
        copy — the engine memoizes the underlying array)."""
        return self._req.replay_ids().copy()


class Scheduler:
    """FIFO queue + admission/eviction policy over a fixed slot count."""

    def __init__(self, max_queue: int):
        if max_queue < 0:
            raise ValueError(f"max_queue must be >= 0, got {max_queue}")
        self.max_queue = max_queue
        self.queue: Deque[Request] = deque()

    @property
    def depth(self) -> int:
        return len(self.queue)

    def offer(self, req: Request) -> None:
        """Enqueue, or raise :class:`QueueFull` (admission control)."""
        if len(self.queue) >= self.max_queue:
            raise QueueFull(
                f"queue full ({len(self.queue)}/{self.max_queue} waiting); "
                f"request rejected — shed load or raise max_queue")
        self.queue.append(req)

    def expire_queued(self, now: float) -> List[Request]:
        """Drop queued requests already past their deadline (they would
        only waste a prefill).  Returns the dropped requests."""
        dead = [r for r in self.queue if r.expired(now)]
        if dead:
            self.queue = deque(r for r in self.queue if not r.expired(now))
            for r in dead:
                r.state = EVICTED
                r.finish_reason = "deadline"
        return dead

    def shed_overload(self, now: float,
                      eta_first_token_s: Callable[[int], float]
                      ) -> List[Request]:
        """Deadline-aware overload shedding: evict queued requests whose
        deadline will expire before they could plausibly produce a first
        token.  ``eta_first_token_s(position)`` is the engine's estimate
        of seconds until the request at queue ``position`` would deliver
        its first token (derived from measured tick times — see
        :func:`eta_first_token` for the model, including how a
        multi-pool tier folds the router's admission cadence in); a
        request with ``deadline < now + eta`` only wastes a prefill, so
        it is shed NOW — at admission-decision time, not after burning
        a slot.  Deadline-less requests are never shed."""
        shed: List[Request] = []
        keep: Deque[Request] = deque()
        pos = 0
        for r in self.queue:
            if (r.deadline is not None
                    and now + eta_first_token_s(pos) > r.deadline):
                r.state = EVICTED
                r.finish_reason = "shed"
                shed.append(r)
            else:
                keep.append(r)
                pos += 1
        self.queue = keep
        return shed

    def requeue_front(self, reqs: List[Request]) -> None:
        """Put recovered in-flight requests back at the HEAD of the
        queue, preserving their order — they were already admitted once,
        so re-admission after an arena rebuild must neither lose their
        FIFO priority nor be refused by ``max_queue`` backpressure."""
        for r in reversed(reqs):
            r.state = QUEUED
            r.slot = None
            self.queue.appendleft(r)

    def peek(self) -> Optional[Request]:
        """The request :meth:`pop_for_admission` would return, without
        removing it — the engine checks the head's BLOCK need against
        the paged arena before committing to admission (FIFO: a head
        the free blocks cannot cover blocks the line rather than being
        overtaken, so admission order stays deterministic)."""
        return self.queue[0] if self.queue else None

    def pop_for_admission(self) -> Optional[Request]:
        """Next request to prefill into a free slot (FIFO), or None."""
        return self.queue.popleft() if self.queue else None
