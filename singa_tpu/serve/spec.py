"""Speculative decoding over the paged KV arena (ISSUE 13).

The committed hlocost baselines classify decode as MEMORY-bound: every
decode dispatch streams the whole weight + KV working set through HBM
to emit one token per slot.  Speculative decoding raises
tokens-per-dispatch instead of trying to make the dispatch cheaper: a
small DRAFT model proposes ``k`` tokens per slot, and the target model
scores all ``k + 1`` window positions in ONE compute-denser **verify**
dispatch — the third gated program, next to prefill and decode.

How one verify round works (all of it inside the single compiled
``verify`` program; ``k`` is a trace-time constant):

1. **propose** — the draft runs ``k + 1`` single-token steps over its
   own dense cache view (gathered through the SAME block tables as the
   target's: the draft arena is a parallel per-layer block pool in
   :class:`~singa_tpu.serve.slots.BlockPool`), greedily picking
   ``d1..dk`` from the pending token ``t0``.  The extra (k+1)-th step
   exists only to write ``dk``'s draft KV, so a fully-accepted round
   leaves no gap in the draft cache.
2. **verify** — the target scores the window ``[t0, d1..dk]`` at
   per-slot positions in one ``(num_slots, k+1)`` forward
   (``cached_sdpa``'s per-row ``limit`` and the per-row RoPE offset
   vector already support multi-token windows), writing the window's
   KV for BOTH arenas via the fixed-shape multi-token scatter
   (``ops.kv_cache.scatter_tokens_kv``).
3. **accept + commit/rollback** — the accepted run is the longest
   prefix of proposals matching the target's own greedy picks; the
   delivered tokens are literally the TARGET's argmaxes
   (``cand[:, :a+1]``), which is why speculative greedy streams are
   bitwise identical to ``generate()`` *by construction* — the draft
   can only change HOW MANY target picks one dispatch yields, never
   their values.  Rejected positions are rolled back by TRUNCATING the
   slot's position/attention limit (``new_pos = pos + a + 1``): the
   stale KV past the new limit is unreachable (masked by every
   reader's validity window) and is overwritten by the next round —
   no arena reshape, no scrubbing, no per-``k`` program.

Fault containment (site ``serve.verify``, registered in
``faults/sites.py``): an injected/transient verify failure past the
retry budget falls back to a PLAIN decode tick for that round instead
of wedging the slot or rebuilding the arena — the accepted stream is
unaffected (plain decode is the same target argmax), at the cost of a
gap in the draft cache at the fallback position, which can only lower
the accept rate of later rounds, never change accepted tokens.

Draft quality is strictly a PERFORMANCE knob: a perfect draft
(self-speculation, ``draft_model is model``) accepts everything and
delivers ``k + 1`` tokens per dispatch; an adversarial draft accepts
nothing and the engine still makes one target-correct token of
progress per round (tests/test_spec.py proves both ends bitwise equal
to ``generate()``).
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from ..models._generate import decode_step, resume_step
from ..obs import events
from ..obs import trace as obs_trace
from ..ops import kv_cache as kv_ops

__all__ = ["make_spec_prefill", "make_verify", "verify_round",
           "VerifyDispatchFailed", "resume_on_row", "scatter_chunk"]


class VerifyDispatchFailed(RuntimeError):
    """The verify DISPATCH died past its retry budget (injection site
    ``serve.verify`` or a real pre-launch transient).  The only
    exception :meth:`ServeEngine._spec_tick` converts into a
    plain-decode fallback: at that point nothing was committed, so a
    plain tick on the untouched arena is safe.  Any failure AFTER the
    dispatch (result fetch, delivery) propagates unchanged instead —
    the round is half-committed and only the step-level arena recovery
    may touch it (falling back there would decode the new pending
    token at a stale position and silently diverge the stream)."""


def resume_on_row(resume, params, buffers, ids, pos, row, caches):
    """Gather ``row``'s dense per-layer view and run ``resume`` (a
    ``models._generate.resume_step`` closure) on it at traced offset
    ``pos`` — the shared first half of every prefill-chunk program
    (plain AND speculative), so the two engines' prefill semantics can
    never drift apart."""
    dense = [kv_ops.gather_block_kv(ck, cv, row) for ck, cv in caches]
    return resume(params, buffers, ids, pos, dense)


def scatter_chunk(row, pos, caches, dense, block_size):
    """Scatter the ONE physical block a prefill chunk filled back into
    the paged arena — the shared second half of every prefill-chunk
    program (see :func:`resume_on_row`)."""
    bs = block_size
    wb = jax.lax.dynamic_index_in_dim(row[0], pos // bs, keepdims=False)
    new = []
    for (ck, cv), (dk, dv) in zip(caches, dense):
        kb = jax.lax.dynamic_slice_in_dim(dk[0], pos, bs, axis=0)
        vb = jax.lax.dynamic_slice_in_dim(dv[0], pos, bs, axis=0)
        new.append(kv_ops.scatter_block_kv(ck, cv, wb, kb, vb))
    return new


def make_spec_prefill(model, draft, block_size: int):
    """The spec engine's prefill-chunk closure: identical to the plain
    engine's (gather the slot's dense view, run the cached forward at
    the traced offset, pick the chunk's last token in-program, scatter
    ONE block back) — plus the same chunk through the DRAFT model into
    the draft arena, so a prefilled slot always has both caches warm.
    The draft's chunk logits are unused (the TARGET picks the first
    token) and XLA dead-code-eliminates its lm_head."""
    bs = block_size
    resume = resume_step(model)
    dresume = resume_step(draft)

    def prefill_chunk_spec(params, buffers, dparams, dbuffers, ids, pos,
                           last_idx, slot, tables, toks, caches, dcaches):
        row = jax.lax.dynamic_index_in_dim(tables, slot, axis=0,
                                           keepdims=True)       # (1, MB)
        logits, dense = resume_on_row(resume, params, buffers, ids,
                                      pos, row, caches)
        last = jax.lax.dynamic_slice_in_dim(
            logits, last_idx, 1, axis=1)[:, 0, :]
        tok = jnp.argmax(last, axis=-1).astype(jnp.int32)[0]
        toks = toks.at[slot].set(tok)
        new = scatter_chunk(row, pos, caches, dense, bs)
        _, ddense = resume_on_row(dresume, dparams, dbuffers, ids, pos,
                                  row, dcaches)
        dnew = scatter_chunk(row, pos, dcaches, ddense, bs)
        return toks, new, dnew

    return prefill_chunk_spec


def make_verify(model, draft, spec_k: int, block_size: int):
    """Build the verify program's closure (see the module docstring for
    the three phases).  Returns
    ``(accepted, cand, new_toks, new_pos, caches, dcaches)`` where
    ``accepted`` is the per-slot count of accepted PROPOSALS (0..k) and
    ``cand`` is the (num_slots, k+1) matrix of the target's greedy
    picks — the host delivers ``cand[slot, :accepted+1]``.  Inactive
    slots are masked exactly like plain decode: positions clamped to 0,
    every window write redirected to the null block, token entries and
    positions frozen."""
    k, bs = spec_k, block_size
    dec_d = decode_step(draft)
    res_t = resume_step(model)

    def verify(params, buffers, dparams, dbuffers, toks, pos, active,
               tables, caches, dcaches):
        posc = jnp.where(active, pos, 0)

        # -- 1. draft propose: k+1 single-token greedy steps ------------
        ddense = [kv_ops.gather_block_kv(ck, cv, tables)
                  for ck, cv in dcaches]
        cur, dp = toks, posc
        props = []
        for j in range(k + 1):
            dlogits, ddense = dec_d(dparams, dbuffers, cur[:, None], dp,
                                    ddense)
            cur = jnp.argmax(dlogits.astype(jnp.float32),
                             axis=-1).astype(jnp.int32)
            if j < k:
                props.append(cur)
            dp = dp + 1
        props = jnp.stack(props, axis=1)                       # (S, k)

        # window scatter targets, shared by both arenas: position
        # pos+j lands at [table[slot, (pos+j)//bs], (pos+j)%bs]
        wpos = posc[:, None] + jnp.arange(k + 1)[None, :]      # (S, k+1)
        wblk = jnp.take_along_axis(tables, wpos // bs, axis=1)
        wblk = jnp.where(active[:, None], wblk, 0)
        woff = jnp.where(active[:, None], wpos % bs, 0)

        def window(c, p):
            return jax.lax.dynamic_slice_in_dim(c, p, k + 1, axis=0)

        def scatter_window(cs, dense):
            new = []
            for (ck, cv), (dk, dv) in zip(cs, dense):
                kw = jax.vmap(window)(dk, posc)        # (S, k+1, K, D)
                vw = jax.vmap(window)(dv, posc)
                new.append(kv_ops.scatter_tokens_kv(ck, cv, wblk, woff,
                                                    kw, vw))
            return new

        new_d = scatter_window(dcaches, ddense)

        # -- 2. target verify: one (S, k+1) forward ---------------------
        win_ids = jnp.concatenate([toks[:, None], props], axis=1)
        dense = [kv_ops.gather_block_kv(ck, cv, tables)
                 for ck, cv in caches]
        logits, dense = res_t(params, buffers, win_ids, posc, dense)
        cand = jnp.argmax(logits.astype(jnp.float32),
                          axis=-1).astype(jnp.int32)           # (S, k+1)
        new_t = scatter_window(caches, dense)

        # -- 3. accept the longest matching greedy prefix ---------------
        match = (props == cand[:, :k]).astype(jnp.int32)
        acc = jnp.cumprod(match, axis=1).sum(axis=1)           # (S,) 0..k
        new_tok = jnp.take_along_axis(cand, acc[:, None], axis=1)[:, 0]
        new_toks = jnp.where(active, new_tok, toks)
        # rollback IS this truncation: rejected positions stay written
        # but sit past the new limit, unreachable and overwritten next
        new_pos = jnp.where(active, posc + acc + 1, pos)
        acc = jnp.where(active, acc, 0)
        return acc, cand, new_toks, new_pos, new_t, new_d

    return verify


def verify_round(engine) -> int:
    """One speculative tick over the whole arena: dispatch the verify
    program, then commit each slot's accepted run host-side — deliver
    ``accepted + 1`` tokens (the target's own picks) in stream order,
    stopping early at EOS/budget like any other delivery path.  Same
    subsystem-package access pattern as ``disagg/handoff.py``: this is
    the implementation behind ``ServeEngine._spec_tick``."""
    from ..utils import failure
    k = engine.spec_k
    t0 = time.perf_counter()
    with events.span("serve.verify", active=len(engine._running), k=k):
        try:
            out = engine._dispatch(
                "serve.verify", engine._verify,
                (engine._params, engine._buffers, engine._dparams,
                 engine._dbuffers, engine._toks, engine.pool.pos,
                 engine.pool.active, engine.pool.tables,
                 engine.pool.caches, engine.pool.draft_caches),
                active=len(engine._running))
        except (RuntimeError, OSError) as e:
            if isinstance(e, failure.FailureDetected):
                raise
            # ONLY the un-committed dispatch failure is fallback-safe;
            # everything past this point is half-committed state whose
            # failures must escalate (see VerifyDispatchFailed)
            raise VerifyDispatchFailed(
                f"{type(e).__name__}: {e}") from e
        (acc_v, cand_v, engine._toks, new_pos, engine.pool.caches,
         engine.pool.draft_caches) = out
        acc = np.asarray(acc_v)    # singalint: disable=SGL008 the designed per-tick sync: one (S,) + one (S, k+1) int fetch commits a whole verify round
        cand = np.asarray(cand_v)
    engine.pool.pos = new_pos
    dt = time.perf_counter() - t0
    delivered = 0
    for slot in list(engine._running):
        req = engine._running[slot]
        a = int(acc[slot])
        run = [int(t) for t in cand[slot, :a + 1]]
        done = False
        n = 0
        with obs_trace.activate(req.trace_id):
            engine.metrics.on_spec_round(k, a)
            for tok in run:
                done = req.deliver(tok)
                n += 1
                engine.metrics.on_deliver(req.rid, len(req.tokens))
                if done:
                    # budget/EOS mid-run: the leftover accepted tokens
                    # are DISCARDED (generate() would never have
                    # produced them either) and the slot is released
                    break
            # per-token cost = this dispatch's latency amortized over
            # the tokens it yielded for this slot (a plain decode tick
            # is the n == 1 case of the same definition)
            for _ in range(n):
                engine.metrics.on_token(dt / n)
            engine.metrics.on_slot_dispatch(n)
        if req.on_token is not None:
            for tok in run[:n]:
                req.on_token(tok, req.handle)
        delivered += n
        if done:
            engine._finalize(slot)
    return delivered
