"""Multi-process disaggregated serving: supervisor + worker pools
(ISSUE 18).

The in-process tier (:mod:`~singa_tpu.serve.disagg`) proves the
prefill/decode split's SCHEDULING story — but its N workers share one
Python interpreter, so N engines never buy parallel compute.  This
module is the same tier shape with the workers in their own OS
processes:

* :func:`build_proc_pools` mirrors ``build_pools``: it spawns N + M
  worker processes (:mod:`.procworker` — one ``ServeEngine`` each,
  platform pinned via the canonical ``utils.virtcpu`` recipe), each of
  which builds its model DETERMINISTICALLY from a seeded
  ``module:callable`` builder (same weights in every process — the
  repro-friendly stand-in for weight shipping), compiles its own
  program set, and reports readiness (model key, compile counts, wall
  time) over the control channel.
* :class:`ProcRouter` mirrors ``Router`` over the framed RPC
  (:mod:`.rpc`): submissions route least-loaded, tier rounds pipeline
  (ticks are SENT to every worker before any reply is awaited, so
  worker compute overlaps), and finished prefills hand off through the
  versioned wire codec (:mod:`.codec`) — host-staged gather →
  serialize → socket → digest check → donated scatter via the
  existing ``inject_handoff``.
* **resilience** is replay, same as the in-process tier: the
  supervisor's :class:`ProcHandle` mirror (prompt + tokens so far) is
  the authoritative copy of every live request, so a dead worker, a
  torn frame (``serve.transport`` chaos), or a failed inject re-routes
  the request via ``resubmit`` on a surviving worker and greedy replay
  keeps the stream bitwise identical.  A torn transfer is NEVER
  injected — the codec rejects it by digest before any engine state is
  touched.
* **elastic pools** — :meth:`ProcRouter.resize` grows (background
  spawn, adopted at a step boundary) or shrinks (drain RPC: the worker
  hands its in-flight requests back as host state, they replay on
  survivors, then the process exits) either pool at runtime; an
  :class:`~singa_tpu.serve.net.elastic.ElasticPolicy` can drive it
  from queue-depth / parked-handoff signals.  ``serve.resize`` faults
  abort a resize cleanly without touching the worker set.
* **self-healing** (ISSUE 19) — a supervisor-side liveness layer
  (per-op RPC deadlines + ``heartbeat`` probes of quiet workers)
  declares a HUNG worker dead as readily as a crashed one, and every
  death funnels into the same path: in-flight requests replay bitwise
  on survivors immediately, then a replacement is respawned in the
  background toward the role's target size and adopted at a step
  boundary exactly like elastic grow (``serve.respawn`` incident).
  K deaths of one role inside a window trip a crash-loop circuit
  breaker (``serve.crashloop`` incident): the tier stops respawning
  that role and degrades to the surviving pools until an explicit
  ``resize()`` closes the breaker.  See docs/robustness.md
  "Self-healing".

Observability: each worker writes its own event sink
(``<base>.<worker>``) and every RPC frame carries the contextvar trace
id, so ``tools/obsq trace <id> --events '<base>*'`` renders one
timeline across all processes.
"""

from __future__ import annotations

import base64
import itertools
import json
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time
import warnings
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ... import faults
from ...faults.plan import InjectedFault
from ...obs import events
from ...obs import flight as obs_flight
from ...obs import record as obs_record
from ...obs import trace as obs_trace
from ...obs.events import _Hist
from ..engine import EngineClosed
from ..scheduler import (EVICTED, FAILED, FINISHED, QUEUED, RUNNING,
                         QueueFull)
from ..disagg.router import SLOClass, _merged_summary
from . import rpc

__all__ = ["WorkerProc", "ProcHandle", "ProcRouter", "ProcTierMetrics",
           "build_proc_pools", "WorkerDied"]

_POOL_SEQ = itertools.count()

#: Per-op RPC deadlines (seconds).  One blanket generous timeout (the
#: old ``_CALL_TIMEOUT_S = 120``) meant a silently wedged worker could
#: stall the tier for two minutes before anything noticed; each op now
#: gets the deadline its work actually justifies:
#:
#: * ``heartbeat``/``health`` are header-only probes a healthy worker
#:   answers in microseconds — seconds of allowance is pure scheduler
#:   slack, so a hang is DECLARED in seconds, not minutes;
#: * ``submit``/``resubmit``/``withdraw``/``chaos`` are queue/plan
#:   mutations: host-side bookkeeping only, no device dispatch;
#: * ``tick`` runs one engine round and ``handoff`` moves KV over the
#:   wire — tens of seconds of honest compute on a loaded CPU box;
#: * ``drain``/``shutdown`` bound how long an elastic scale-down or a
#:   close waits before escalating to a kill.
#:
#: A worker's FIRST few ticks and FIRST handoff may pay a jit compile
#: — those calls escalate to ``_COMPILE_TIMEOUT_S`` (see
#: :meth:`WorkerProc.op_timeout`) instead of inflating every
#: steady-state deadline.
_OP_TIMEOUTS: Dict[str, float] = {
    "heartbeat": 5.0,
    "health": 10.0,
    "submit": 15.0,
    "resubmit": 15.0,
    "withdraw": 15.0,
    "chaos": 15.0,
    "tick": 60.0,
    "handoff": 60.0,
    "drain": 60.0,
    "shutdown": 30.0,
}
#: ops missing from the table (forward compatibility) keep the old
#: blanket deadline
_DEFAULT_TIMEOUT_S = 120.0
#: first-dispatch escalation: jit compiles happen on a worker's first
#: prefill/decode/handoff dispatches, NOT at ready (ready only proves
#: the build), so early ticks/handoffs get the compile budget
_COMPILE_TIMEOUT_S = 300.0
#: how many ok ticks before a worker's tick deadline drops from the
#: compile-aware budget to the steady-state one (the prefill, decode
#: and spec program variants each compile on a different early tick)
_WARMUP_TICKS = 4


class WorkerDied(ConnectionError):
    """The worker process behind an RPC went away (socket error, RPC
    timeout, or an op reply the supervisor treats as fatal)."""


class WorkerProc:
    """Supervisor-side proxy for one worker process: the Popen, the
    connected control socket, and the rid mapping (each process draws
    request ids from its own counter, so the supervisor keys everything
    by its OWN qid and maps per-worker)."""

    def __init__(self, name: str, role: str, proc: subprocess.Popen,
                 sock: socket.socket, fabric: "_Fabric"):
        self.name = name
        self.role = role
        self.proc = proc
        self.sock = sock
        self.fabric = fabric
        self.alive = True
        self.load = 0
        self.pid: Optional[int] = None
        self.model_key: Optional[str] = None
        self.compiles: Optional[dict] = None
        self.ready_ms: Optional[float] = None
        #: a timed-out / errored socket may sit mid-frame — the next
        #: recv on it would misparse stale bytes as a fresh reply, so
        #: the FIRST WorkerDied poisons the connection for good and
        #: every later use fails fast without touching the socket
        self.poisoned = False
        #: monotonic time of the last successful round trip — the
        #: host-side heartbeat age (``ProcRouter._check_liveness``)
        self.last_ok = time.monotonic()
        #: successful ticks / handoff ops so far — drives the
        #: compile-aware deadline escalation in :meth:`op_timeout`
        self.ok_ticks = 0
        self.ok_handoffs = 0
        #: worker-local rid -> supervisor qid for every request this
        #: worker currently owns
        self.wrids: Dict[int, int] = {}

    def op_timeout(self, op: str) -> float:
        """The per-op deadline (``_OP_TIMEOUTS``), compile-aware: a
        worker's early ticks and first handoff escalate to the fabric's
        compile budget because jit compiles happen on first dispatch,
        not at ready."""
        t = self.fabric.op_timeouts.get(op, _DEFAULT_TIMEOUT_S)
        if op == "tick" and self.ok_ticks < _WARMUP_TICKS:
            return max(t, self.fabric.compile_timeout_s)
        if op == "handoff" and self.ok_handoffs < 1:
            return max(t, self.fabric.compile_timeout_s)
        return t

    def _usable(self) -> None:
        if self.poisoned:
            raise WorkerDied(
                f"worker {self.name}: connection poisoned by an "
                f"earlier timeout/socket error (stream may be "
                f"mid-frame); refusing further RPC")

    def _poison(self, e: BaseException) -> WorkerDied:
        self.poisoned = True
        return WorkerDied(
            f"worker {self.name}: {type(e).__name__}: {e}")

    def call(self, header: Dict[str, Any], payload: bytes = b"", *,
             timeout: Optional[float] = None
             ) -> Tuple[Dict[str, Any], bytes]:
        """One RPC round trip; any socket-level failure is a
        :class:`WorkerDied` (the caller escalates to worker death).
        ``timeout=None`` resolves from the per-op table via
        :meth:`op_timeout`."""
        self._usable()
        if timeout is None:
            timeout = self.op_timeout(str(header.get("op", "")))
        try:
            rep, data = rpc.call(self.sock, header, payload,
                                 timeout=timeout)
        except (rpc.RPCError, socket.timeout, OSError) as e:
            raise self._poison(e) from e
        self.last_ok = time.monotonic()
        return rep, data

    def send(self, header: Dict[str, Any], payload: bytes = b"") -> None:
        self._usable()
        try:
            rpc.send_frame(self.sock, header, payload)
        except OSError as e:
            raise self._poison(e) from e

    def recv(self, *, timeout: Optional[float] = None
             ) -> Tuple[Dict[str, Any], bytes]:
        self._usable()
        try:
            rep, data = rpc.recv_frame(
                self.sock,
                timeout=_DEFAULT_TIMEOUT_S if timeout is None
                else timeout)
        except (rpc.RPCError, socket.timeout, OSError) as e:
            raise self._poison(e) from e
        self.last_ok = time.monotonic()
        return rep, data

    def __repr__(self) -> str:
        return (f"WorkerProc({self.name!r}, {self.role}, "
                f"{'alive' if self.alive else 'DEAD'}, "
                f"pid={self.pid}, load={self.load})")


class _Fabric:
    """Spawn plumbing shared by a tier's worker processes: one AF_UNIX
    listener in a private tempdir, the worker config template (so
    elastic grow spawns clones), and the spawn lock that keeps a
    background grow from racing a close."""

    def __init__(self, worker_cfg: dict, *,
                 spawn_timeout_s: float = 300.0,
                 faults_env: Optional[Dict[str, str]] = None,
                 op_timeouts: Optional[Dict[str, float]] = None,
                 compile_timeout_s: float = _COMPILE_TIMEOUT_S):
        self.dir = tempfile.mkdtemp(prefix="singa-net-")
        self.sock_path = os.path.join(self.dir, "sup.sock")
        self.listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self.listener.bind(self.sock_path)
        self.listener.listen(64)
        self.worker_cfg = worker_cfg
        self.spawn_timeout_s = spawn_timeout_s
        self.faults_env = dict(faults_env or {})
        #: per-op RPC deadlines — the documented defaults with any
        #: caller overrides on top (tests/chaos runs shrink them)
        self.op_timeouts = {**_OP_TIMEOUTS, **(op_timeouts or {})}
        self.compile_timeout_s = float(compile_timeout_s)
        self.obs_base: Optional[str] = None
        #: every Popen this fabric ever spawned — the chaos driver's
        #: no-orphan invariant audits this ledger (each entry must be
        #: an adopted pool member or already reaped)
        self.procs: List[subprocess.Popen] = []
        self._lock = threading.Lock()
        self._name_seq = {"prefill": itertools.count(),
                          "decode": itertools.count()}
        self._gen = next(_POOL_SEQ)
        self._closed = False

    def next_name(self, role: str) -> str:
        return f"{role[0]}{next(self._name_seq[role])}-mp{self._gen}"

    def _child_env(self) -> Dict[str, str]:
        env = dict(os.environ)
        # never inherit the supervisor's fault plan or event sink: a
        # forwarded plan would double-inject (both sides of one RPC),
        # and a shared sink file would interleave process writes
        for k in ("SINGA_FAULTS", "SINGA_FAULTS_SEED", "SINGA_OBS"):
            env.pop(k, None)
        env.update(self.faults_env)
        # children import singa_tpu (and the default tools.loadgen
        # builder) by module path — anchor the repo root regardless of
        # the supervisor's cwd
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
        pp = env.get("PYTHONPATH")
        env["PYTHONPATH"] = root if not pp else f"{root}{os.pathsep}{pp}"
        return env

    def spawn_many(self, specs: List[Tuple[str, str]]
                   ) -> List[WorkerProc]:
        """Spawn one worker process per (name, role), wait for each to
        connect + hello + ready.  All children build concurrently; the
        supervisor pays max(build) wall time, not the sum."""
        with self._lock:
            if self._closed:
                raise RuntimeError("fabric is closed")
            procs: Dict[str, subprocess.Popen] = {}
            for name, role in specs:
                cfg = dict(self.worker_cfg)
                if self.obs_base:
                    cfg = dict(cfg, obs_path=f"{self.obs_base}.{name}")
                arg = base64.b64encode(
                    json.dumps(cfg).encode()).decode()
                procs[name] = subprocess.Popen(
                    [sys.executable, "-m",
                     "singa_tpu.serve.net.procworker",
                     "--sock", self.sock_path, "--name", name,
                     "--role", role, "--config", arg],
                    env=self._child_env())
            self.procs.extend(procs.values())
            by_name: Dict[str, WorkerProc] = {}
            deadline = time.monotonic() + self.spawn_timeout_s
            roles = dict(specs)
            try:
                self.listener.settimeout(self.spawn_timeout_s)
                while len(by_name) < len(specs):
                    conn, _ = self.listener.accept()
                    hello, _ = rpc.recv_frame(
                        conn, timeout=max(1.0,
                                          deadline - time.monotonic()))
                    name = hello.get("name")
                    if hello.get("op") != "hello" or name not in roles \
                            or name in by_name:
                        conn.close()
                        continue
                    w = WorkerProc(name, roles[name], procs[name], conn,
                                   self)
                    w.pid = hello.get("pid")
                    by_name[name] = w
                out = []
                for name, _role in specs:
                    w = by_name[name]
                    ready, _ = w.recv(
                        timeout=max(1.0, deadline - time.monotonic()))
                    if ready.get("op") != "ready" or not ready.get("ok"):
                        raise WorkerDied(
                            f"worker {name} failed to become ready: "
                            f"{ready}")
                    w.model_key = ready.get("model_key")
                    w.compiles = ready.get("compiles")
                    w.ready_ms = ready.get("ready_ms")
                    out.append(w)
                return out
            except socket.timeout:
                self._reap(procs.values())
                raise WorkerDied(
                    f"spawn timed out: {sorted(set(roles) - set(by_name))} "
                    f"never connected within {self.spawn_timeout_s:.0f}s"
                ) from None
            except BaseException:
                # never leave half-spawned children behind: a failed
                # batch is reaped wholesale (the no-orphan invariant)
                self._reap(procs.values())
                raise
            finally:
                self.listener.settimeout(None)

    @staticmethod
    def _reap(procs) -> None:
        for p in procs:
            try:
                p.kill()
            except OSError:
                pass
        for p in procs:
            try:
                p.wait(timeout=10.0)
            except (subprocess.TimeoutExpired, OSError):
                pass

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            try:
                self.listener.close()
            except OSError:
                pass
            for p in (self.sock_path,):
                try:
                    os.unlink(p)
                except OSError:
                    pass
            try:
                os.rmdir(self.dir)
            except OSError:
                pass


def build_proc_pools(model_spec, n_prefill: int, n_decode: int, *,
                     num_slots: int = 4, max_len: int = 64,
                     block_size: int = 16,
                     num_blocks: Optional[int] = None,
                     share_prefix: bool = True,
                     max_queue: Optional[int] = None,
                     record_store: Optional[str] = None,
                     devices: int = 1,
                     obs_base: Optional[str] = None,
                     faults_env: Optional[Dict[str, str]] = None,
                     spawn_timeout_s: float = 300.0,
                     self_spec_k: int = 0,
                     op_timeouts: Optional[Dict[str, float]] = None,
                     compile_timeout_s: float = _COMPILE_TIMEOUT_S,
                     **engine_kwargs
                     ) -> Tuple[List[WorkerProc], List[WorkerProc]]:
    """(prefill_workers, decode_workers) as OS processes — the
    multi-process mirror of ``disagg.build_pools``.

    ``model_spec`` is either a ``"module:callable"`` builder string or
    ``{"builder": "mod:fn", "kwargs": {...}}``; every worker calls it
    under the same seed discipline, so all processes hold identical
    weights.  ``obs_base`` (default: the supervisor's own configured
    sink path) gives each worker a ``<base>.<name>`` event sink;
    ``faults_env`` forwards a ``SINGA_FAULTS`` plan to the CHILDREN
    (worker-side chaos) — by default children are scrubbed of the
    supervisor's plan so one spec never injects on both sides of an
    RPC.  ``op_timeouts`` overrides entries of the per-op RPC deadline
    table (``_OP_TIMEOUTS``) and ``compile_timeout_s`` the
    first-dispatch escalation budget — chaos tests shrink both so hang
    detection is measured in seconds."""
    if n_prefill < 1 or n_decode < 1:
        raise ValueError(
            f"a tier needs at least one worker per pool, got "
            f"{n_prefill} prefill / {n_decode} decode")
    if isinstance(model_spec, str):
        model_spec = {"builder": model_spec}
    worker_cfg = {
        "model": model_spec,
        "devices": int(devices),
        "self_spec_k": int(self_spec_k),
        "engine": dict(num_slots=num_slots, max_len=max_len,
                       block_size=block_size, num_blocks=num_blocks,
                       share_prefix=share_prefix, max_queue=max_queue,
                       record_store=record_store, **engine_kwargs),
    }
    fabric = _Fabric(worker_cfg, spawn_timeout_s=spawn_timeout_s,
                     faults_env=faults_env, op_timeouts=op_timeouts,
                     compile_timeout_s=compile_timeout_s)
    if obs_base is None:
        sink = events.get_sink()
        obs_base = getattr(sink, "path", None)
    fabric.obs_base = obs_base
    specs = [(fabric.next_name("prefill"), "prefill")
             for _ in range(n_prefill)]
    specs += [(fabric.next_name("decode"), "decode")
              for _ in range(n_decode)]
    try:
        workers = fabric.spawn_many(specs)
    except BaseException:
        fabric.close()
        raise
    return ([w for w in workers if w.role == "prefill"],
            [w for w in workers if w.role == "decode"])


class ProcHandle:
    """Supervisor-side mirror of one request — the SAME user-facing
    surface as :class:`~singa_tpu.serve.scheduler.RequestHandle`, but
    the state lives here (fed by tick deltas) because the worker that
    owns the request can die: the mirror is what replay resubmits
    from."""

    def __init__(self, qid: int, prompt_ids, max_new_tokens: int,
                 deadline_s: Optional[float], eos_id: Optional[int],
                 trace_id: str, on_token=None):
        self.qid = qid
        self._prompt = np.asarray(prompt_ids, dtype=np.int32).reshape(-1)
        self._max_new = int(max_new_tokens)
        self._deadline = (None if deadline_s is None
                          else time.monotonic() + float(deadline_s))
        self._eos = eos_id
        self._trace = trace_id
        self._on_token = on_token
        self._tokens: List[int] = []
        self._state = QUEUED
        self._finish_reason: Optional[str] = None
        self._error: Optional[str] = None
        self._ttft_s: Optional[float] = None

    # -- RequestHandle surface ---------------------------------------------
    @property
    def rid(self) -> int:
        return self.qid

    @property
    def trace_id(self) -> Optional[str]:
        return self._trace

    @property
    def status(self) -> str:
        return self._state

    @property
    def done(self) -> bool:
        return self._state in (FINISHED, EVICTED, FAILED)

    @property
    def failed(self) -> bool:
        return self._state == FAILED

    @property
    def error(self) -> Optional[str]:
        return self._error

    @property
    def finish_reason(self) -> Optional[str]:
        return self._finish_reason

    @property
    def tokens(self) -> List[int]:
        return list(self._tokens)

    @property
    def ttft_s(self) -> Optional[float]:
        return self._ttft_s

    def result(self) -> np.ndarray:
        return np.concatenate(
            [self._prompt, np.asarray(self._tokens, np.int32)])

    # -- mirror feed (tick deltas) -----------------------------------------
    def _append(self, tok: int) -> None:
        self._tokens.append(int(tok))
        if self._state == QUEUED:
            self._state = RUNNING
        if self._on_token is not None:
            self._on_token(int(tok))

    def _finish(self, state: str, reason: Optional[str],
                error: Optional[str]) -> None:
        self._state = state
        self._finish_reason = reason
        self._error = error

    def _deadline_rem(self) -> Optional[float]:
        return (None if self._deadline is None
                else self._deadline - time.monotonic())


class ProcTierMetrics:
    """Tier metrics over worker processes: the supervisor's own
    counters plus ``health`` fan-out aggregation — same ``snapshot()``
    shape as the in-process :class:`TierMetrics` (what loadgen
    consumes), with the transport extras on top.  Workers that were
    drained away (elastic shrink) leave their FINAL health snapshot
    cached here, so tier totals and latency percentiles survive pool
    churn."""

    def __init__(self, router: "ProcRouter"):
        self._router = router
        self.handoffs = 0
        self.reroutes = 0
        self.door_rejected = 0
        self.quota_rejected = 0
        self.worker_deaths = 0
        self.respawns = 0
        self.crashloops = 0
        self.steps = 0
        self.resizes = 0
        self.resizes_aborted = 0
        self.torn_frames = 0
        self.wire_bytes = 0
        self._handoff = _Hist()
        self._ser = _Hist()
        #: worker name -> last health reply (alive workers refresh on
        #: every snapshot; retired/dead workers keep their last)
        self._health: Dict[str, dict] = {}

    # -- supervisor-side events --------------------------------------------
    def on_handoff(self, wait_ms: float, nbytes: int,
                   ser_ms: float) -> None:
        self.handoffs += 1
        self.wire_bytes += int(nbytes)
        self._handoff.observe(wait_ms)
        self._ser.observe(ser_ms)
        events.counter("serve.handoffs", 1)
        events.counter("serve.handoff_wire_bytes", nbytes)
        events.histogram("serve.handoff_ms", wait_ms)
        events.histogram("serve.handoff_ser_ms", ser_ms)

    def on_reroute(self) -> None:
        self.reroutes += 1
        events.counter("serve.rerouted", 1)

    def on_torn_frame(self) -> None:
        self.torn_frames += 1
        events.counter("serve.torn_frame", 1)

    def on_door_reject(self) -> None:
        self.door_rejected += 1
        events.counter("serve.rejected", 1, reason="tier_full")

    def on_worker_death(self, worker: str) -> None:
        self.worker_deaths += 1
        events.counter("serve.worker_dead", 1, worker=worker)

    def on_respawn(self, worker: str) -> None:
        self.respawns += 1
        events.counter("serve.respawn", 1, worker=worker)

    def on_crashloop(self, role: str) -> None:
        self.crashloops += 1
        events.counter("serve.crashloop", 1, role=role)

    def on_resize(self, kind: str) -> None:
        self.resizes += 1
        events.counter("serve.resize", 1, kind=kind)

    def on_step(self) -> None:
        self.steps += 1

    def handoff_summary(self) -> Optional[dict]:
        return self._handoff.summary()

    # -- aggregation -------------------------------------------------------
    def refresh_health(self) -> None:
        for w in self._router.workers():
            if not w.alive:
                continue
            try:
                rep, _ = w.call({"op": "health"})
            except WorkerDied as e:
                self._router._worker_death(w, str(e))
                continue
            if rep.get("ok"):
                self._health[w.name] = rep

    def retire(self, w: WorkerProc) -> None:
        """Fetch (or keep) ``w``'s final health before it leaves the
        tier — best-effort: a dead worker keeps whatever was cached."""
        if not w.alive:
            return
        try:
            rep, _ = w.call({"op": "health"})
            if rep.get("ok"):
                self._health[w.name] = rep
        except WorkerDied:
            pass

    def snapshot(self) -> dict:
        self.refresh_health()
        healths = list(self._health.values())
        snaps = [h["snapshot"] for h in healths]

        def total(key: str) -> int:
            return sum(s[key] for s in snaps)

        def merge(key: str) -> Dict[str, int]:
            out: Dict[str, int] = {}
            for s in snaps:
                for k, v in s[key].items():
                    out[k] = out.get(k, 0) + v
            return out

        def merged(key: str) -> Optional[dict]:
            hists = []
            for h in healths:
                hist = _Hist()
                hist.samples = list(h.get(key) or [])
                hists.append(hist)
            return _merged_summary(hists)

        spec_proposed = total("spec_proposed")
        disp = sum(s["slot_dispatches"] for s in snaps)
        disp_tokens = sum(s["slot_dispatch_tokens"] for s in snaps)
        return {
            "submitted": total("submitted"),
            "spec_rounds": total("spec_rounds"),
            "spec_proposed": spec_proposed,
            "spec_accepted": total("spec_accepted"),
            "spec_fallbacks": total("spec_fallbacks"),
            "accept_rate": (total("spec_accepted") / spec_proposed
                            if spec_proposed else None),
            "tokens_per_dispatch": (disp_tokens / disp if disp else None),
            "admitted": total("admitted"),
            "rejected": self.door_rejected + self.quota_rejected,
            "evicted": merge("evicted"),
            "retries": merge("retries"),
            "quarantined": total("quarantined"),
            "recoveries": total("recoveries"),
            "preempted": total("preempted"),
            "prefix_hits": total("prefix_hits"),
            "prefix_hit_tokens": total("prefix_hit_tokens"),
            "steps": self.steps,
            "ttft_ms": merged("ttft_samples"),
            "token_ms": merged("token_samples"),
            "handoffs": self.handoffs,
            "handoff_ms": self.handoff_summary(),
            "reroutes": self.reroutes,
            "worker_deaths": self.worker_deaths,
            "respawns": self.respawns,
        }


class ProcRouter:
    """Front door + tick loop over worker PROCESSES — the
    :class:`~singa_tpu.serve.disagg.router.Router` contract (submit /
    step / drain / close, tier_stats, metrics.snapshot) for a tier
    whose workers live behind :mod:`.rpc`.

        pw, dw = build_proc_pools("tools.loadgen:_build_model", 2, 1)
        tier = ProcRouter(pw, dw)
        h = tier.submit(prompt, max_new_tokens=16)
        tier.run_until_idle()
        tier.close()
    """

    def __init__(self, prefill_workers: List[WorkerProc],
                 decode_workers: List[WorkerProc], *,
                 slo_classes: Optional[Dict[str, SLOClass]] = None,
                 record_store: Optional[str] = None,
                 run_id: Optional[str] = None,
                 policy=None,
                 heartbeat_every_s: float = 2.0,
                 respawn: bool = True,
                 respawn_backoff_s: float = 0.5,
                 respawn_backoff_cap_s: float = 30.0,
                 breaker_k: int = 3,
                 breaker_window_s: float = 60.0):
        self.prefill = list(prefill_workers)
        self.decode = list(decode_workers)
        if not self.prefill or not self.decode:
            raise ValueError("a tier needs at least one prefill and one "
                             "decode worker")
        names = [w.name for w in self.workers()]
        if len(set(names)) != len(names):
            raise ValueError(f"worker names must be unique, got {names}")
        self.fabric = self.prefill[0].fabric
        self.slo_classes = dict(slo_classes or {})
        self.record_store = record_store
        self.run_id = run_id or obs_record.new_run_id("mptier")
        self.policy = policy
        self.metrics = ProcTierMetrics(self)
        #: the supervisor's OWN flight ring (a dead worker process
        #: cannot be asked for its ring — the survivor's view is the
        #: incident evidence)
        self.flight = obs_flight.register(obs_flight.FlightRecorder())
        self.model_key = next(
            (w.model_key for w in self.workers() if w.model_key), None)
        self._seq = itertools.count()
        self._incident_seq = itertools.count()
        self._handles: Dict[int, ProcHandle] = {}
        self._where: Dict[int, WorkerProc] = {}
        self._ready_at: Dict[int, float] = {}
        self._tick_ewma: Optional[float] = None
        #: ready prefills that found no decode capacity last round —
        #: the decode-pool backpressure signal the elastic policy reads
        self.parked = 0
        self._staged: List[WorkerProc] = []
        self._staged_lock = threading.Lock()
        self._spawn_threads: List[threading.Thread] = []
        self._draining = False
        self._closed = False
        # -- self-healing knobs + state (ISSUE 19) ------------------
        #: probe an alive worker whose last successful RPC is older
        #: than this (host half of the ``utils.failure.Heartbeat``
        #: contract: beat age > deadline → dead, crash or no crash)
        self.heartbeat_every_s = float(heartbeat_every_s)
        #: automatic respawn of dead workers toward the role target
        self.respawn = bool(respawn)
        self.respawn_backoff_s = float(respawn_backoff_s)
        self.respawn_backoff_cap_s = float(respawn_backoff_cap_s)
        #: crash-loop circuit breaker: ``breaker_k`` deaths of one role
        #: inside ``breaker_window_s`` → stop respawning that role
        self.breaker_k = int(breaker_k)
        self.breaker_window_s = float(breaker_window_s)
        #: per-role pool-size goal — seeded from the constructor
        #: pools, moved ONLY by :meth:`resize`; respawn restores
        #: toward it and adoption dismisses any surplus beyond it
        self._target = {"prefill": len(self.prefill),
                        "decode": len(self.decode)}
        #: in-flight background spawns per role (guarded by
        #: ``_staged_lock``, like ``_staged`` — together they make the
        #: "already on its way" count resize/respawn dedupe against)
        self._spawning = {"prefill": 0, "decode": 0}
        #: consecutive failed respawn attempts → exponential backoff
        self._respawn_fails = {"prefill": 0, "decode": 0}
        self._respawn_not_before = {"prefill": 0.0, "decode": 0.0}
        #: recent death timestamps per role (breaker window evidence)
        self._death_times: Dict[str, List[float]] = {"prefill": [],
                                                     "decode": []}
        self._breaker_open = {"prefill": False, "decode": False}

    # -- introspection -----------------------------------------------------
    def workers(self) -> List[WorkerProc]:
        return self.prefill + self.decode

    @property
    def pending(self) -> int:
        """Requests the tier still owes an outcome — counted from the
        supervisor mirror (the authoritative copy), not from worker
        loads (a dead worker's load is meaningless)."""
        return sum(1 for h in self._handles.values() if not h.done)

    def worker(self, name: str) -> WorkerProc:
        for w in self.workers():
            if w.name == name:
                return w
        raise KeyError(f"no worker named {name!r} "
                       f"(have: {[w.name for w in self.workers()]})")

    def tier_stats(self) -> dict:
        summ = self.metrics.handoff_summary() or {}
        return {
            "prefill_workers": len(self.prefill),
            "decode_workers": len(self.decode),
            "handoffs": self.metrics.handoffs,
            "handoff_p99_ms": round(summ.get("p99", 0.0), 3),
        }

    def transport_stats(self) -> dict:
        """The ``serve_load`` transport field trio (obs/schema.py
        ``_SERVE_TRANSPORT_FIELDS``) — what ``loadgen --procs`` stamps
        into its records."""
        ser = self.metrics._ser.summary() or {}
        return {
            "handoff_wire_bytes": self.metrics.wire_bytes,
            "handoff_ser_ms_p99": round(ser.get("p99", 0.0), 3),
            "resizes": self.metrics.resizes,
        }

    # -- submission --------------------------------------------------------
    def submit(self, prompt_ids, *, max_new_tokens: int,
               tenant: Optional[str] = None,
               slo: Optional[str] = None,
               deadline_s: Optional[float] = None,
               eos_id: Optional[int] = None,
               on_token=None) -> ProcHandle:
        if self._closed:
            raise EngineClosed("submit() on a closed tier")
        if self._draining:
            raise EngineClosed("tier is draining — new submissions are "
                               "refused while in-flight requests complete")
        faults.fire("serve.router", tenant=tenant or "", slo=slo or "")
        if slo is not None:
            cls = self.slo_classes.get(slo)
            if cls is None:
                raise ValueError(
                    f"unknown SLO class {slo!r} (registered: "
                    f"{sorted(self.slo_classes)})")
            if deadline_s is None:
                deadline_s = cls.deadline_s
        qid = next(self._seq)
        trace_id = f"{self.run_id}/q{qid}"
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        for w in self._route_order(self._prefill_pool()):
            try:
                rep, _ = w.call({"op": "submit", "trace": trace_id,
                                 "prompt": prompt.tolist(),
                                 "max_new_tokens": int(max_new_tokens),
                                 "deadline_s": deadline_s,
                                 "eos_id": eos_id})
            except WorkerDied as e:
                self._worker_death(w, str(e))
                continue
            if not rep.get("ok"):
                err = rep.get("err", "")
                if err.startswith("value_error"):
                    raise ValueError(err.partition(":")[2].strip()
                                     or err)
                continue   # queue_full / draining: try the next worker
            h = ProcHandle(qid, prompt, max_new_tokens, deadline_s,
                           eos_id, trace_id, on_token)
            with obs_trace.activate(trace_id):
                events.counter("serve.route", 1, worker=w.name,
                               role=w.role)
            self._handles[qid] = h
            self._where[qid] = w
            w.wrids[rep["rid"]] = qid
            w.load = rep.get("pending", w.load + 1)
            return h
        self.metrics.on_door_reject()
        raise QueueFull(
            "every prefill worker's queue is at capacity; request "
            "rejected — shed load, raise max_queue, or add workers")

    def _prefill_pool(self) -> List[WorkerProc]:
        alive = [w for w in self.prefill if w.alive]
        return alive or [w for w in self.decode if w.alive]

    @staticmethod
    def _route_order(pool: List[WorkerProc]) -> List[WorkerProc]:
        return sorted(pool, key=lambda w: (w.load, w.name))

    # -- the tier round ----------------------------------------------------
    def step(self) -> int:
        """One tier round, PIPELINED: tick frames go out to every
        worker in a pool before any reply is awaited, so the worker
        processes compute concurrently — this is where N processes buy
        wall-clock the in-process tier cannot."""
        if self._closed:
            raise EngineClosed("step() on a closed tier")
        t0 = time.monotonic()
        delivered = 0
        with events.span("serve.tier_step"):
            self._adopt_staged()
            self._prune()
            self._check_liveness()
            decode_alive = [w for w in self.decode if w.alive]
            ready_map: Dict[str, List[dict]] = {}
            delivered += self._tick_pool(
                [w for w in self.prefill if w.alive],
                decode=not decode_alive, ready_map=ready_map)
            self._drain_prefills(ready_map)
            delivered += self._tick_pool(
                [w for w in self.decode if w.alive], decode=True)
            if not any(w.alive for w in self.workers()) and self.pending:
                raise RuntimeError(
                    "every worker in the tier is dead; cannot serve "
                    "the remaining requests")
            if self.policy is not None:
                want = self.policy.decide(self)
                if want:
                    self.resize(**want)
            self._respawn_tick()
            dt = time.monotonic() - t0
            self._tick_ewma = dt if self._tick_ewma is None else \
                0.8 * self._tick_ewma + 0.2 * dt
            self.metrics.on_step()
        return delivered

    def _tick_pool(self, pool: List[WorkerProc], *, decode: bool,
                   ready_map: Optional[Dict[str, List[dict]]] = None
                   ) -> int:
        delivered = 0
        sent: List[WorkerProc] = []
        for w in pool:
            try:
                w.send({"op": "tick", "decode": decode,
                        "tick_hint_s": self._tick_ewma})
                sent.append(w)
            except WorkerDied as e:
                self._worker_death(w, str(e))
        for w in sent:
            if not w.alive:
                continue
            try:
                rep, _ = w.recv(timeout=w.op_timeout("tick"))
            except WorkerDied as e:
                self._worker_death(w, str(e))
                continue
            if not rep.get("ok"):
                self._worker_death(w, f"tick: {rep.get('err')}")
                continue
            w.ok_ticks += 1
            delivered += rep.get("delivered", 0)
            w.load = rep.get("pending", w.load)
            self._apply_delta(w, rep.get("delta", ()))
            if ready_map is not None and rep.get("ready"):
                ready_map[w.name] = rep["ready"]
        return delivered

    def _apply_delta(self, w: WorkerProc, delta) -> None:
        for e in delta:
            qid = w.wrids.get(e["rid"])
            h = self._handles.get(qid)
            if h is None:
                continue
            for t in e.get("toks", ()):
                h._append(t)
            if h._ttft_s is None and e.get("ttft_s") is not None:
                h._ttft_s = e["ttft_s"]
            if e.get("done"):
                h._finish(e.get("state", FINISHED),
                          e.get("finish_reason"), e.get("error"))
                w.wrids.pop(e["rid"], None)

    def run_until_idle(self, max_steps: Optional[int] = None) -> None:
        n = 0
        while self.pending:
            self.step()
            n += 1
            if max_steps is not None and n >= max_steps:
                break

    def drain(self, max_steps: Optional[int] = None) -> None:
        self._draining = True
        self.run_until_idle(max_steps=max_steps)

    def close(self) -> None:
        """Drain, shut every worker process down (RPC shutdown, then
        wait), join any in-flight grow spawns, release the fabric.
        Idempotent."""
        if self._closed:
            return
        self.respawn = False   # a closing tier never heals itself
        self.drain()
        self._closed = True
        for t in self._spawn_threads:
            t.join(timeout=self.fabric.spawn_timeout_s)
        self._adopt_staged(force=True)
        for w in self.workers():
            if not w.alive:
                continue
            try:
                w.call({"op": "shutdown"}, timeout=30.0)
            except WorkerDied:
                pass
            w.alive = False
            try:
                w.proc.wait(timeout=30.0)
            except subprocess.TimeoutExpired:
                w.proc.kill()
            try:
                w.sock.close()
            except OSError:
                pass
        self.fabric.close()

    def __enter__(self) -> "ProcRouter":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    # -- handoff over the wire ---------------------------------------------
    def _drain_prefills(self, ready_map: Dict[str, List[dict]]) -> None:
        now = time.monotonic()
        decode_alive = [w for w in self.decode if w.alive]
        parked = 0
        for w in [p for p in self.prefill if p.alive]:
            for ent in ready_map.get(w.name, ()):
                qid = w.wrids.get(ent["rid"])
                h = self._handles.get(qid)
                if h is None:
                    continue
                if qid not in self._ready_at:
                    self._ready_at[qid] = now
                if not decode_alive:
                    parked += 1
                    continue
                dst = None
                for d in self._route_order(decode_alive):
                    try:
                        rep, _ = d.call({
                            "op": "handoff", "dir": "probe",
                            "prompt": h._prompt.tolist(),
                            "n_blocks": ent["n_blocks"],
                            "prompt_keys": ent["prompt_keys"]})
                    except WorkerDied as e:
                        self._worker_death(d, str(e))
                        continue
                    if rep.get("ok") and rep.get("accept"):
                        dst = d
                        break
                if dst is None:
                    parked += 1
                    continue
                self._handoff(w, ent, dst, qid)
                if not w.alive:
                    break   # rest of this worker's entries re-routed
        self.parked = parked

    def _handoff(self, src: WorkerProc, ent: dict, dst: WorkerProc,
                 qid: int) -> None:
        h = self._handles[qid]
        ready = self._ready_at.get(qid)
        wait_ms = 0.0 if ready is None else \
            (time.monotonic() - ready) * 1e3
        with obs_trace.activate(h.trace_id):
            try:
                faults.fire("serve.handoff", rid=qid, src=src.name,
                            dst=dst.name)
            except InjectedFault as e:
                # pre-extract: the request still sits in its source
                # slot — withdraw it there, replay elsewhere
                self._withdraw_quiet(src, ent)
                self._replay(qid, f"handoff {src.name}->{dst.name}: "
                                  f"{type(e).__name__}: {e}")
                return
            with events.span("serve.handoff", src=src.name,
                             dst=dst.name, rid=qid):
                try:
                    rep, wire = src.call({"op": "handoff",
                                          "dir": "extract",
                                          "slot": ent["slot"],
                                          "rid": ent["rid"]})
                except InjectedFault as e:
                    # transport fault on the extract round trip: the
                    # reply (and the KV in it) is gone; whether the
                    # worker already released the slot is unknowable,
                    # so treat the KV as lost and replay
                    self._withdraw_quiet(src, ent)
                    self._replay(qid, f"transport(extract): {e}")
                    return
                except WorkerDied as e:
                    self._worker_death(src, str(e))
                    return   # death replay already covered qid
                if not rep.get("ok"):
                    self._withdraw_quiet(src, ent)
                    self._replay(qid, f"extract: {rep.get('err')}")
                    return
                src.wrids.pop(ent["rid"], None)
                src.load = max(0, src.load - 1)
                src.ok_handoffs += 1
                try:
                    rep2, _ = dst.call({"op": "handoff",
                                        "dir": "inject"}, wire)
                except InjectedFault as e:
                    self._replay(qid, f"transport(inject): {e}")
                    return
                except WorkerDied as e:
                    self._worker_death(dst, str(e))
                    self._replay(qid, f"inject: worker died: {e}")
                    return
                if not rep2.get("ok"):
                    if rep2.get("err") == "torn_frame":
                        self.metrics.on_torn_frame()
                    self._replay(qid, f"inject: {rep2.get('err')}")
                    return
                if not rep2.get("injected"):
                    # capacity vanished between probe and inject
                    self._replay(qid, "inject: capacity vanished",
                                 count_reroute=False)
                    return
        self._ready_at.pop(qid, None)
        self._where[qid] = dst
        dst.wrids[rep2["rid"]] = qid
        dst.load += 1
        dst.ok_handoffs += 1
        self.metrics.on_handoff(
            wait_ms, len(wire),
            float(rep.get("ser_ms", 0.0)) + float(rep2.get("deser_ms",
                                                           0.0)))

    def _withdraw_quiet(self, src: WorkerProc, ent: dict) -> None:
        """Best-effort release of a source slot after a failed handoff
        (the request replays elsewhere regardless)."""
        if not src.alive:
            return
        try:
            src.call({"op": "withdraw", "slot": ent["slot"],
                      "rid": ent["rid"]})
        except WorkerDied as e:
            self._worker_death(src, str(e))
            return
        src.wrids.pop(ent["rid"], None)
        src.load = max(0, src.load - 1)

    # -- replay (re-route) -------------------------------------------------
    def _replay(self, qid: int, reason: str, *,
                count_reroute: bool = True, incident: bool = True,
                warn: bool = True) -> None:
        """Re-admit the request behind ``qid`` from the supervisor
        mirror (prompt + tokens so far) on the least-loaded surviving
        prefill worker — greedy replay keeps its stream bitwise
        identical; ``resubmit`` bypasses queue backpressure because the
        request was already admitted once."""
        h = self._handles.get(qid)
        if h is None or h.done:
            return
        if count_reroute:
            self.metrics.on_reroute()
        if warn:
            warnings.warn(f"serve.net: re-routing request {qid} "
                          f"({reason}); it will re-prefill from "
                          f"prompt + tokens so far", stacklevel=2)
        self._ready_at.pop(qid, None)
        placed = False
        while not placed:
            pool = self._prefill_pool()
            if not pool:
                raise RuntimeError(
                    f"no alive worker to re-route request {qid} to")
            w = self._route_order(pool)[0]
            try:
                rep, _ = w.call({"op": "resubmit", "trace": h.trace_id,
                                 "prompt": h._prompt.tolist(),
                                 "tokens": list(h._tokens),
                                 "max_new_tokens": h._max_new,
                                 "deadline_s": h._deadline_rem(),
                                 "eos_id": h._eos,
                                 "ttft_s": h._ttft_s})
            except WorkerDied as e:
                self._worker_death(w, str(e))
                continue
            if not rep.get("ok"):
                raise RuntimeError(
                    f"replay of request {qid} refused by worker "
                    f"{w.name}: {rep.get('err')}")
            w.wrids[rep["rid"]] = qid
            w.load = rep.get("pending", w.load + 1)
            self._where[qid] = w
            placed = True
        if incident:
            self._incident(
                "serve.handoff", reason, f"req:{qid}", "rerouted", 0,
                flight_ref=self._flight_dump("serve.handoff", reason))

    # -- worker death ------------------------------------------------------
    def kill_worker(self, name: str, reason: str = "killed") -> None:
        """Operations/chaos hook: declare ``name`` dead now (its
        process is terminated) — flight dump, incident record, and
        every request placed on it replays on the survivors."""
        self._worker_death(self.worker(name), reason)

    def _worker_death(self, w: WorkerProc, reason: str) -> None:
        if not w.alive:
            return
        w.alive = False
        self.metrics.on_worker_death(w.name)
        try:
            # SIGKILL, not SIGTERM: a HUNG worker (the liveness layer's
            # whole reason to exist) may be wedged in a way that never
            # services SIGTERM — e.g. SIGSTOPped, or spinning with
            # signals blocked.  Kill is the only verdict that sticks,
            # and the wait() reaps the zombie so the chaos driver's
            # no-orphan audit sees a clean ledger.
            w.proc.kill()
        except OSError:
            pass
        try:
            w.proc.wait(timeout=10.0)
        except (subprocess.TimeoutExpired, OSError):
            pass
        try:
            w.sock.close()
        except OSError:
            pass
        warnings.warn(f"serve.net: worker {w.name} died ({reason}); "
                      f"re-routing its in-flight requests", stacklevel=2)
        self.flight.note("error", "serve.worker_dead", worker=w.name,
                         reason=reason)
        ref = self._flight_dump("serve.router",
                                f"worker {w.name} death: {reason}")
        victims = [qid for qid, ww in self._where.items()
                   if ww is w and not self._handles[qid].done]
        w.wrids.clear()
        # newest first: each resubmit prepends on the survivor, so the
        # oldest request ends up at the head — FIFO survives the death
        for qid in sorted(victims, reverse=True):
            self._replay(qid, f"worker {w.name} death",
                         count_reroute=True, incident=False, warn=False)
        self._incident("serve.router", "worker_death", w.name,
                       "rerouted", len(victims), flight_ref=ref)
        self._on_death_respawn(w.role)

    # -- self-healing: liveness, respawn, crash-loop breaker ---------------
    def _check_liveness(self) -> None:
        """Supervisor-side heartbeat (the host half of the
        ``utils.failure.Heartbeat`` contract): any alive worker whose
        last successful RPC is older than ``heartbeat_every_s`` gets a
        header-only ``heartbeat`` probe on a fast deadline.  A worker
        that cannot answer within seconds is declared dead even though
        its PROCESS may still exist — a hang and a crash converge on
        the same :class:`WorkerDied` funnel (``_worker_death``).  In a
        busy tier every tick refreshes ``last_ok``, so probes only
        ride when a worker has been quiet; a worker that hangs MID
        tick is caught by the tick deadline instead."""
        now = time.monotonic()
        for w in self.workers():
            if not w.alive or now - w.last_ok < self.heartbeat_every_s:
                continue
            try:
                rep, _ = w.call({"op": "heartbeat"})
            except WorkerDied as e:
                self._worker_death(w, f"heartbeat: {e}")
                continue
            if not rep.get("ok"):
                self._worker_death(w, f"heartbeat: {rep.get('err')}")

    def _on_death_respawn(self, role: str) -> None:
        """Death-path respawn bookkeeping: record the death for the
        breaker window, trip the crash-loop breaker at ``breaker_k``
        deaths in ``breaker_window_s`` (→ ``serve.crashloop`` incident,
        the role degrades to the surviving pools instead of
        spawn-spinning), else schedule a replacement immediately."""
        if not self.respawn or self._closed or self._draining:
            return
        now = time.monotonic()
        times = [t for t in self._death_times[role]
                 if now - t <= self.breaker_window_s]
        times.append(now)
        self._death_times[role] = times
        if self._breaker_open[role]:
            return
        if len(times) >= self.breaker_k:
            self._breaker_open[role] = True
            self.metrics.on_crashloop(role)
            warnings.warn(
                f"serve.net: {role} pool is crash-looping "
                f"({len(times)} deaths in {self.breaker_window_s:.0f}s)"
                f"; respawn breaker OPEN — the tier degrades to "
                f"survivors until an explicit resize()", stacklevel=2)
            self.flight.note("error", "serve.crashloop", role=role,
                             deaths=len(times),
                             window_s=self.breaker_window_s)
            self._incident(
                "serve.crashloop", "crash_loop", role, "degraded",
                len(times),
                flight_ref=self._flight_dump(
                    "serve.crashloop",
                    f"{role}: {len(times)} deaths in "
                    f"{self.breaker_window_s:.0f}s"))
            return
        self._respawn_tick()

    def _respawn_tick(self) -> None:
        """Schedule background replacement spawns for any role below
        its target.  Runs at every step boundary AND straight from the
        death path, so a failed attempt is retried (after its capped
        exponential backoff) without needing another death to notice
        the deficit.  The spawn itself happens on a ``net-respawner``
        thread — in-flight requests have ALREADY replayed on survivors
        by the time this runs, so nothing waits on the slow spawn —
        and the newcomer is adopted at a step boundary exactly like
        elastic grow."""
        if not self.respawn or self._closed or self._draining:
            return
        now = time.monotonic()
        for role, pool in (("prefill", self.prefill),
                           ("decode", self.decode)):
            if self._breaker_open[role]:
                continue
            alive = sum(1 for w in pool if w.alive)
            with self._staged_lock:
                if now < self._respawn_not_before[role]:
                    continue
                staged = sum(1 for w in self._staged if w.role == role)
                spawning = self._spawning[role]
            deficit = self._target[role] - (alive + staged + spawning)
            if deficit <= 0:
                continue
            try:
                # the ``serve.respawn`` seam: an error here is a failed
                # attempt (counts toward backoff), a hang delays the
                # respawn decision — the spawn itself is exercised by
                # killing the spawned worker, not by this site
                faults.fire("serve.respawn", role=role, n=deficit)
            except InjectedFault as e:
                self._respawn_failed(role, e)
                continue
            self._respawn(role, deficit)

    def _respawn(self, role: str, n: int) -> None:
        specs = [(self.fabric.next_name(role), role) for _ in range(n)]
        with self._staged_lock:
            self._spawning[role] += n

        def respawn() -> None:
            workers, err = [], None
            try:
                workers = self.fabric.spawn_many(specs)
            except (WorkerDied, RuntimeError, OSError) as e:
                err = e
            with self._staged_lock:
                self._spawning[role] -= n
                if err is None:
                    self._respawn_fails[role] = 0
                    self._respawn_not_before[role] = 0.0
                    for w in workers:
                        w.is_respawn = True
                    self._staged.extend(workers)
            if err is not None:
                self._respawn_failed(role, err)

        t = threading.Thread(target=respawn, name="net-respawner",
                             daemon=True)
        self._spawn_threads.append(t)
        t.start()

    def _respawn_failed(self, role: str, err: BaseException) -> None:
        with self._staged_lock:
            self._respawn_fails[role] += 1
            fails = self._respawn_fails[role]
            backoff = min(self.respawn_backoff_cap_s,
                          self.respawn_backoff_s * 2.0 ** (fails - 1))
            self._respawn_not_before[role] = time.monotonic() + backoff
        warnings.warn(
            f"serve.net: {role} respawn failed "
            f"({type(err).__name__}: {err}); attempt {fails}, next "
            f"retry backs off {backoff:.2f}s", stacklevel=2)

    def breaker_state(self) -> Dict[str, bool]:
        """Operations/test introspection: which roles the crash-loop
        breaker has given up on (cleared by an explicit resize)."""
        return dict(self._breaker_open)

    def heal_state(self) -> dict:
        """One consistent snapshot of the self-healing machinery —
        what a chaos driver polls to decide the tier has settled:
        per-role alive counts vs targets, staged-but-not-adopted and
        in-flight spawn counts, and the breaker state."""
        with self._staged_lock:
            staged = {r: sum(1 for w in self._staged if w.role == r)
                      for r in ("prefill", "decode")}
            spawning = dict(self._spawning)
        return {
            "alive": {"prefill": sum(1 for w in self.prefill
                                     if w.alive),
                      "decode": sum(1 for w in self.decode if w.alive)},
            "target": dict(self._target),
            "staged": staged,
            "spawning": spawning,
            "breaker": dict(self._breaker_open),
        }

    # -- elastic resize ----------------------------------------------------
    def resize(self, n_prefill: Optional[int] = None,
               n_decode: Optional[int] = None) -> bool:
        """Grow/shrink the pools toward the requested sizes.  Shrink is
        synchronous (drain → replay → shutdown); grow spawns in a
        background thread and the new workers are adopted at the next
        ``step()`` boundary.  Returns False when the ``serve.resize``
        fault aborts the resize (the tier is untouched — resizes are
        idempotent shape goals, the policy simply re-evaluates
        later)."""
        if self._closed:
            raise EngineClosed("resize() on a closed tier")
        try:
            faults.fire("serve.resize",
                        prefill=-1 if n_prefill is None else n_prefill,
                        decode=-1 if n_decode is None else n_decode)
        except InjectedFault as e:
            self.metrics.resizes_aborted += 1
            warnings.warn(f"serve.net: resize aborted by injected "
                          f"fault ({e})", stacklevel=2)
            return False
        changed = False
        for pool, role, want in ((self.prefill, "prefill", n_prefill),
                                 (self.decode, "decode", n_decode)):
            if want is None:
                continue
            want = max(1, int(want))   # never below one worker per pool
            self._target[role] = want
            # an explicit resize is an operator decision: the role gets
            # a clean slate — breaker closed, backoff forgotten
            self._breaker_open[role] = False
            self._death_times[role] = []
            alive = [w for w in pool if w.alive]
            with self._staged_lock:
                self._respawn_fails[role] = 0
                self._respawn_not_before[role] = 0.0
                staged = sum(1 for w in self._staged if w.role == role)
                spawning = self._spawning[role]
            # grow against everything already on its way (staged +
            # in-flight spawns), not just the alive count — a shrink
            # below that sum is settled at adoption time, where the
            # target guard dismisses the surplus newcomer cleanly
            # (the respawn-vs-shrink race cannot double-adopt)
            have = len(alive) + staged + spawning
            if want > have:
                self._grow(role, want - have)
                changed = True
            elif want < len(alive):
                # drain the youngest first (oldest workers keep the
                # warmest prefix caches)
                for w in sorted(alive, key=lambda w: w.name,
                                reverse=True)[:len(alive) - want]:
                    self._drain_worker(w, pool)
                changed = True
        if changed:
            self.metrics.on_resize(
                f"p{len(self.prefill)}d{len(self.decode)}")
        return changed

    def _grow(self, role: str, n: int) -> None:
        specs = [(self.fabric.next_name(role), role) for _ in range(n)]
        with self._staged_lock:
            self._spawning[role] += n

        def spawn() -> None:
            workers, err = [], None
            try:
                workers = self.fabric.spawn_many(specs)
            except (WorkerDied, RuntimeError, OSError) as e:
                err = e
            with self._staged_lock:
                self._spawning[role] -= n
                self._staged.extend(workers)
            if err is not None:
                warnings.warn(f"serve.net: grow spawn failed: {err}",
                              stacklevel=2)

        t = threading.Thread(target=spawn, name="net-spawner",
                             daemon=True)
        self._spawn_threads.append(t)
        t.start()

    def _adopt_staged(self, force: bool = False) -> None:
        with self._staged_lock:
            staged, self._staged = self._staged, []
        for w in staged:
            if self._closed and not force:
                continue
            pool = self.prefill if w.role == "prefill" else self.decode
            alive = sum(1 for x in pool if x.alive)
            if not force and alive >= self._target[w.role]:
                # the target moved while this spawn was in flight (an
                # elastic shrink racing a respawn/grow): the newcomer
                # is surplus — dismiss it cleanly instead of
                # double-adopting, and no process is orphaned
                self._dismiss(w, "surplus to target after resize")
                continue
            pool.append(w)
            events.counter("serve.worker_adopted", 1, worker=w.name,
                           role=w.role)
            self.flight.note("counter", "serve.worker_adopted",
                             worker=w.name, role=w.role)
            if getattr(w, "is_respawn", False):
                # the self-healing receipt: replacement adopted, pool
                # back toward target — incident + flight evidence
                self.metrics.on_respawn(w.name)
                self._incident(
                    "serve.respawn", "respawn", w.name, "respawned",
                    0, flight_ref=self._flight_dump(
                        "serve.respawn",
                        f"worker {w.name} adopted as replacement"))

    def _dismiss(self, w: WorkerProc, reason: str) -> None:
        """Shut down a spawned-but-never-adopted worker cleanly (it
        owns no requests — nothing to replay)."""
        self.flight.note("counter", "serve.worker_dismissed",
                         worker=w.name, reason=reason)
        try:
            w.call({"op": "shutdown"})
        except WorkerDied:
            pass
        w.alive = False
        try:
            w.proc.wait(timeout=30.0)
        except subprocess.TimeoutExpired:
            w.proc.kill()
        try:
            w.sock.close()
        except OSError:
            pass

    def _drain_worker(self, w: WorkerProc, pool: List[WorkerProc]
                      ) -> None:
        """Elastic scale-down of one worker: final health cached (its
        latency samples survive in tier metrics), in-flight requests
        handed back as host state and replayed bitwise on survivors,
        then a clean process exit — recorded as a ``serve.resize``
        incident with the supervisor ring as evidence."""
        pool.remove(w)
        self.metrics.retire(w)
        self.flight.note("counter", "serve.worker_drain", worker=w.name)
        try:
            rep, _ = w.call({"op": "drain"})
        except WorkerDied as e:
            self._worker_death(w, f"drain: {e}")
            return
        victims = []
        for r in rep.get("reqs", ()):
            qid = w.wrids.get(r["rid"])
            if qid is not None and not self._handles[qid].done:
                victims.append(qid)
        w.wrids.clear()
        for qid in sorted(victims, reverse=True):
            self._replay(qid, f"worker {w.name} drained",
                         count_reroute=False, incident=False,
                         warn=False)
        try:
            w.call({"op": "shutdown"}, timeout=30.0)
        except WorkerDied:
            pass
        w.alive = False
        try:
            w.proc.wait(timeout=30.0)
        except subprocess.TimeoutExpired:
            w.proc.kill()
        try:
            w.sock.close()
        except OSError:
            pass
        self._incident(
            "serve.resize", "drain", w.name, "drained", len(victims),
            flight_ref=self._flight_dump(
                "serve.resize", f"worker {w.name} drained"))

    # -- bookkeeping -------------------------------------------------------
    def _prune(self) -> None:
        for qid, h in list(self._handles.items()):
            if h.done:
                self._handles.pop(qid, None)
                self._where.pop(qid, None)
                self._ready_at.pop(qid, None)
        # dead workers leave the pool lists once their victims have
        # replayed (which happened at death): respawn means pools churn
        # for the tier's whole life, and tier_stats/resize must count
        # the real population, not a graveyard
        for pool in (self.prefill, self.decode):
            if any(not w.alive for w in pool):
                pool[:] = [w for w in pool if w.alive]

    def _flight_dump(self, site: str, reason: str) -> Optional[str]:
        return obs_flight.dump_for_store(self.flight, site,
                                         self.record_store, reason)

    def _incident(self, site: str, fault: str, ref, outcome: str,
                  retries: int, flight_ref: Optional[str] = None
                  ) -> None:
        events.counter("serve.incident", 1, site=site, outcome=outcome)
        if not self.record_store:
            return
        try:
            import jax
            platform = jax.default_backend()
            dev = jax.devices()[0]
            payload = {"site": site, "fault": fault, "ref": ref,
                       "outcome": outcome, "retries": int(retries),
                       "engine_run": self.run_id}
            if flight_ref:
                payload["flight_ref"] = flight_ref
            entry = obs_record.new_entry(
                "incident", platform, platform != "tpu",
                getattr(dev, "device_kind", "") or platform,
                run_id=f"{self.run_id}-inc{next(self._incident_seq)}",
                payload=payload)
            obs_record.RunRecord(self.record_store).append(entry)
        except Exception as e:
            warnings.warn(f"could not append incident record: "
                          f"{type(e).__name__}: {e}", stacklevel=2)
