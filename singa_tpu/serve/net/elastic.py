"""Elastic prefill/decode pool sizing for the multi-process tier
(ISSUE 18).

The :class:`ElasticPolicy` turns the supervisor's per-step signals into
:meth:`ProcRouter.resize` calls:

* **decode backpressure** — finished prefills parked because no decode
  worker can hold their KV (``router.parked``): the tier is producing
  prefills faster than the decode pool drains them → grow decode.
* **prefill pressure** — deep queues on the prefill pool (sum of
  prefill worker loads vs. slot capacity) with an idle decode pool →
  grow prefill.
* **idle** — a tier with nothing pending for ``patience`` consecutive
  checks shrinks the pool that is furthest ABOVE the target share
  toward ``min_per_pool`` (capacity follows load down, not just up).

Grow direction on ambiguous signals consults the committed autotune
knob ``serve.pool_ratio`` (the decode share of the worker budget that
the ``--ratio-sweep`` records showed wins for this model/platform) —
the policy nudges the tier TOWARD that share rather than oscillating.

Decisions are debounced: signals must persist for ``patience``
consecutive checks (one check every ``check_every`` steps) before a
resize fires, and a resize resets the debounce — the supervisor's
``serve.resize`` fault site can still abort any individual resize,
which the policy simply retries at a later check.

Interaction with self-healing (ISSUE 19): a policy resize moves the
supervisor's per-role TARGET, which is also what respawn restores
toward after a worker death — so an elastic shrink that lands while a
respawn spawn is in flight is settled at adoption time (the surplus
newcomer is dismissed against the moved target, never double-adopted),
and an explicit resize hands a crash-looping role a clean slate
(breaker closed, backoff forgotten).  The policy reads ``alive``
worker counts only, so a dead-but-not-yet-pruned worker never inflates
the pool size a decision is based on.
"""

from __future__ import annotations

from typing import Dict, Optional

__all__ = ["ElasticPolicy", "target_decode_share"]


def target_decode_share(model_key: Optional[str] = None) -> float:
    """The committed decode share of the worker budget for this
    platform (autotune knob ``serve.pool_ratio``; falls back to the
    shipped default of 0.5 when no table entry matches)."""
    try:
        import jax

        from ...autotune import table as _table
        knobs = _table.resolve("serve", model_key or "unknown",
                               jax.default_backend(), {})
        return float(knobs.get("pool_ratio", 0.5))
    except Exception:  # pragma: no cover - autotune table unavailable
        return 0.5


class ElasticPolicy:
    """Debounced grow/shrink policy over a :class:`ProcRouter`; pass as
    ``ProcRouter(..., policy=ElasticPolicy(max_total=4))`` and the tier
    re-evaluates at every ``check_every``-th step."""

    def __init__(self, *, min_per_pool: int = 1, max_total: int = 4,
                 check_every: int = 8, patience: int = 2,
                 decode_share: Optional[float] = None):
        if min_per_pool < 1:
            raise ValueError(f"min_per_pool must be >= 1, "
                             f"got {min_per_pool}")
        if max_total < 2 * min_per_pool:
            raise ValueError(
                f"max_total={max_total} cannot hold {min_per_pool} "
                f"worker(s) per pool")
        self.min_per_pool = int(min_per_pool)
        self.max_total = int(max_total)
        self.check_every = max(1, int(check_every))
        self.patience = max(1, int(patience))
        self._share = decode_share
        self._steps = 0
        self._parked_checks = 0
        self._queued_checks = 0
        self._idle_checks = 0

    def decode_share(self, router) -> float:
        if self._share is None:
            self._share = target_decode_share(
                getattr(router, "model_key", None))
        return self._share

    def decide(self, router) -> Optional[Dict[str, int]]:
        """Called by the supervisor once per tier step; returns resize
        kwargs (``{"n_decode": 3}``) or None."""
        self._steps += 1
        if self._steps % self.check_every:
            return None
        n_p = len([w for w in router.prefill if w.alive])
        n_d = len([w for w in router.decode if w.alive])
        total = n_p + n_d
        parked = getattr(router, "parked", 0)
        queued = sum(w.load for w in router.prefill if w.alive)
        pending = router.pending

        self._parked_checks = self._parked_checks + 1 if parked else 0
        self._queued_checks = (self._queued_checks + 1
                               if queued > 2 * n_p else 0)
        self._idle_checks = self._idle_checks + 1 if not pending else 0

        if self._parked_checks >= self.patience:
            self._parked_checks = 0
            if total < self.max_total:
                return {"n_decode": n_d + 1}
            if n_p > self.min_per_pool and \
                    n_d / total < self.decode_share(router):
                # at the budget: trade a prefill worker for decode
                # capacity, but only while below the committed share
                return {"n_prefill": n_p - 1, "n_decode": n_d + 1}
            return None
        if self._queued_checks >= self.patience:
            self._queued_checks = 0
            if total < self.max_total:
                return {"n_prefill": n_p + 1}
            return None
        if self._idle_checks >= self.patience:
            self._idle_checks = 0
            share = self.decode_share(router)
            # shrink whichever pool is further above the committed
            # share (ties shrink decode — prefill is the front door)
            over_d = n_d - max(self.min_per_pool,
                               round(share * (total - 1)))
            if n_d > self.min_per_pool and over_d >= 0:
                return {"n_decode": n_d - 1}
            if n_p > self.min_per_pool:
                return {"n_prefill": n_p - 1}
        return None
