"""singa_tpu.serve.net — multi-process disaggregated serving
(ISSUE 18).

The in-process tier (:mod:`~singa_tpu.serve.disagg`) with the workers
moved into their own OS processes:

* :mod:`~singa_tpu.serve.net.rpc` — framed request/response protocol
  over local sockets; every frame carries the contextvar trace id, so
  one request's timeline spans process boundaries.
* :mod:`~singa_tpu.serve.net.codec` — versioned, length-prefixed,
  digest-checked binary wire format for ``HandoffPackage`` (a torn
  transfer is never injected; it replays instead).
* :mod:`~singa_tpu.serve.net.procworker` — the worker-process main:
  one ``ServeEngine`` per process, platform-pinned, deterministically
  built, reporting compile/readiness over the control channel.
* :mod:`~singa_tpu.serve.net.supervisor` — :func:`build_proc_pools`
  (mirrors ``build_pools``) and :class:`ProcRouter` (mirrors
  ``Router``), plus elastic grow/shrink of either pool at runtime.
* :mod:`~singa_tpu.serve.net.elastic` — the debounced autoscaling
  policy over SLO/backpressure signals and the committed
  ``serve.pool_ratio`` autotune knob.

See docs/serving.md ("Multi-process serving") for the architecture and
the measurement caveats.
"""

from .codec import (TornFrame, WireError, decode_package,
                    encode_package, probe_package)
from .elastic import ElasticPolicy
from .rpc import RPCError
from .supervisor import (ProcHandle, ProcRouter, ProcTierMetrics,
                         WorkerDied, WorkerProc, build_proc_pools)

__all__ = ["ProcRouter", "ProcHandle", "ProcTierMetrics", "WorkerProc",
           "WorkerDied", "build_proc_pools", "ElasticPolicy",
           "RPCError", "WireError", "TornFrame", "encode_package",
           "decode_package", "probe_package"]
