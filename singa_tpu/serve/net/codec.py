"""Versioned binary wire codec for :class:`HandoffPackage` (ISSUE 18).

A cross-process handoff is the single-engine handoff with a socket in
the middle: the source worker host-stages its ``handoff_gather`` output
(`jax.device_get`), this codec serializes the package to one
length-prefixed frame payload, and the destination worker deserializes
and feeds the result to the existing ``inject_handoff`` path — the
donated-scatter install code is shared with the in-process tier, so the
wire adds representation, not new semantics.

Frame layout (all integers big-endian)::

    MAGIC "SGKV" | u8 version | u32 header_len | header JSON (utf-8)
    | tensor bytes (C-order, concatenated in manifest order)
    | blake2b-128 digest of every byte above

The header carries the request's full host state (prompt, tokens so
far, budget, remaining deadline, trace id) plus the package metadata
(pos, n_blocks, prefix chain keys as hex) and a tensor manifest
(dtype + shape per tensor, target KV pairs first, then draft pairs).

**Torn transfers are never injected**: :func:`decode_package` verifies
the trailing digest over the whole frame before it parses anything
mutable, so a truncated or bit-flipped frame (crash mid-send, the
``serve.transport`` chaos site's ``torn_frame`` kind) raises
:class:`TornFrame` and the supervisor re-routes the request via replay
(prompt + tokens so far re-prefill on a surviving worker — greedy
replay idempotence keeps the stream bitwise, same machinery as
worker death).

The codec is deliberately dumb about device placement: it consumes and
produces HOST numpy arrays (`encode_package` stages with
``device_get``; inject's eager scatters accept numpy slices), so the
bytes on the wire are platform-independent.  ``bfloat16`` round-trips
through the ``ml_dtypes`` numpy extension jax registers.
"""

from __future__ import annotations

import hashlib
import json
import struct
import time
from typing import List, Optional, Tuple

import numpy as np

from ..scheduler import Request
from ..disagg.handoff import HandoffPackage

__all__ = ["WireError", "TornFrame", "encode_package", "decode_package",
           "probe_package", "WIRE_VERSION"]

_MAGIC = b"SGKV"
WIRE_VERSION = 1
_DIGEST_BYTES = 16
_HEAD = struct.Struct(">4sBI")   # magic, version, header_len


class WireError(ValueError):
    """Structurally invalid frame: bad magic, unknown version, or a
    manifest that does not describe the payload.  Distinct from
    :class:`TornFrame` so callers can tell 'wrong protocol' from
    'right protocol, damaged in flight'."""


class TornFrame(WireError):
    """Digest mismatch: the frame was truncated or corrupted between
    encode and decode.  The package MUST NOT be injected — the caller
    re-routes the request via replay instead."""


def _digest(data: bytes) -> bytes:
    return hashlib.blake2b(data, digest_size=_DIGEST_BYTES).digest()


def _host_pairs(pairs, what: str) -> List[Tuple[np.ndarray, np.ndarray]]:
    """device_get a list of (k, v) per-layer views as contiguous host
    arrays, refusing non-array leaves (the int8 QuantKV arena keeps its
    codes+scales structure out of this codec for now — residue)."""
    import jax
    staged = jax.device_get(list(pairs))
    out = []
    for i, pair in enumerate(staged):
        if not (isinstance(pair, (tuple, list)) and len(pair) == 2):
            raise WireError(
                f"{what}[{i}] is not a (k, v) pair — the wire codec "
                f"ships dense array views only (int8 QuantKV arenas "
                f"are not wire-serializable yet)")
        k, v = pair
        if getattr(k, "dtype", None) is None or \
                getattr(v, "dtype", None) is None:
            raise WireError(f"{what}[{i}] leaves are not arrays")
        out.append((np.ascontiguousarray(k), np.ascontiguousarray(v)))
    return out


def encode_package(pkg: HandoffPackage, *, src: Optional[str] = None
                   ) -> bytes:
    """Serialize ``pkg`` to one frame payload (see module docstring).
    ``src`` overrides the package's source-worker tag (the supervisor
    stamps the worker name it extracted from)."""
    req = pkg.req
    kv = _host_pairs(pkg.kv, "kv")
    draft = (_host_pairs(pkg.draft_kv, "draft_kv")
             if pkg.draft_kv is not None else [])
    tensors: List[np.ndarray] = []
    manifest: List[Tuple[str, List[int]]] = []
    for k, v in kv + draft:
        for t in (k, v):
            tensors.append(t)
            manifest.append((str(t.dtype), list(t.shape)))
    deadline_rem = (req.deadline - time.monotonic()
                    if req.deadline is not None else None)
    header = {
        "rid": req.rid,
        "prompt": np.asarray(req.prompt).tolist(),
        "tokens": list(req.tokens),
        "max_new_tokens": req.max_new_tokens,
        "deadline_rem_s": deadline_rem,
        "eos_id": req.eos_id,
        "trace": req.trace_id,
        "ttft_s": req.ttft_s,
        "pos": pkg.pos,
        "n_blocks": pkg.n_blocks,
        "prompt_keys": [k.hex() for k in pkg.prompt_keys],
        "src": src if src is not None else pkg.src,
        "n_kv": len(kv),
        "n_draft": len(draft),
        "tensors": manifest,
    }
    hj = json.dumps(header, separators=(",", ":")).encode()
    parts = [_HEAD.pack(_MAGIC, WIRE_VERSION, len(hj)), hj]
    parts.extend(t.tobytes() for t in tensors)
    body = b"".join(parts)
    return body + _digest(body)


def decode_package(data: bytes) -> HandoffPackage:
    """Parse a frame payload back into a :class:`HandoffPackage` with
    host-numpy KV views, verifying the trailing digest FIRST — a torn
    or corrupted frame raises :class:`TornFrame` before any request
    state is constructed."""
    if len(data) < _HEAD.size + _DIGEST_BYTES:
        raise TornFrame(
            f"frame too short ({len(data)} bytes) — truncated in flight")
    if data[:4] != _MAGIC:
        raise WireError(f"bad magic {data[:4]!r} (want {_MAGIC!r})")
    body, tail = data[:-_DIGEST_BYTES], data[-_DIGEST_BYTES:]
    if _digest(body) != tail:
        raise TornFrame(
            "frame digest mismatch — torn transfer, refusing to inject")
    magic, version, hlen = _HEAD.unpack_from(data, 0)
    if version != WIRE_VERSION:
        raise WireError(f"unknown wire version {version} "
                        f"(this build speaks {WIRE_VERSION})")
    off = _HEAD.size
    if off + hlen > len(body):
        raise WireError("header length exceeds frame")
    header = json.loads(body[off:off + hlen].decode())
    off += hlen
    tensors: List[np.ndarray] = []
    for dtype_name, shape in header["tensors"]:
        dt = np.dtype(dtype_name)
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        nbytes = n * dt.itemsize
        if off + nbytes > len(body):
            raise WireError("tensor manifest exceeds frame payload")
        tensors.append(np.frombuffer(body, dtype=dt, count=n, offset=off)
                       .reshape(shape))
        off += nbytes
    if off != len(body):
        raise WireError(
            f"{len(body) - off} trailing bytes after manifest tensors")
    n_kv, n_draft = int(header["n_kv"]), int(header["n_draft"])
    if len(tensors) != 2 * (n_kv + n_draft):
        raise WireError("tensor count disagrees with layer counts")
    pairs = [(tensors[2 * i], tensors[2 * i + 1])
             for i in range(n_kv + n_draft)]
    kv, draft = pairs[:n_kv], pairs[n_kv:]
    req = Request(np.asarray(header["prompt"], np.int32),
                  header["max_new_tokens"],
                  header["deadline_rem_s"], header["eos_id"], None)
    req.tokens = [int(t) for t in header["tokens"]]
    req.trace_id = header.get("trace")
    req.ttft_s = header.get("ttft_s")
    return HandoffPackage(
        req=req, kv=kv, pos=int(header["pos"]),
        n_blocks=int(header["n_blocks"]),
        prompt_keys=[bytes.fromhex(h) for h in header["prompt_keys"]],
        src=header.get("src", ""),
        draft_kv=draft if n_draft else None)


def probe_package(prompt_ids, n_blocks: int,
                  prompt_keys_hex: List[str]) -> HandoffPackage:
    """A KV-less stand-in package for capacity probes: carries exactly
    the fields ``can_accept_handoff`` reads (prompt, block count,
    prefix chain keys), so a destination worker can answer 'would this
    fit' without the source gathering or shipping a single KV byte.
    Must never be passed to inject."""
    req = Request(np.asarray(prompt_ids, np.int32), 1, None, None, None)
    return HandoffPackage(
        req=req, kv=[], pos=0, n_blocks=int(n_blocks),
        prompt_keys=[bytes.fromhex(h) for h in prompt_keys_hex])
