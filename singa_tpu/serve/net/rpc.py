"""Minimal framed request/response protocol for worker RPC (ISSUE 18).

One frame = a small JSON header plus an optional opaque binary payload::

    u32 header_len | u32 payload_len | header JSON (utf-8) | payload

The header always carries ``op`` (verb) and, when a request context is
active on the sender, ``trace`` — the existing contextvar trace id
(obs.trace), so one request's timeline spans supervisor and worker
sinks and ``tools/obsq trace`` renders it as a single tree across
process boundaries.  Receivers re-activate the frame's trace id around
handling, which is all the cross-process propagation there is.

Verbs (handled in :mod:`.procworker`): ``hello``, ``ready``,
``submit``, ``resubmit``, ``tick``, ``handoff`` (probe / extract /
inject), ``drain``, ``health``, ``heartbeat`` (header-only,
engine-free liveness probe — the supervisor's hang detector, ISSUE
19), ``chaos`` (install a worker-side fault plan — the campaign
driver's seam), ``shutdown``.  Replies echo ``op`` with
``ok`` set; errors ride back as ``{"ok": false, "err": ...}`` rather
than killing the connection.

Deadlines live one layer up: the supervisor resolves a per-op timeout
from its table (``supervisor._OP_TIMEOUTS``, compile-aware) and a
timed-out socket is POISONED there — this module's ``recv_frame``
cannot tell a late reply from a fresh one (frames carry no request
id), so the supervisor-side poisoning contract is what prevents a
stale reply being misread as the answer to a newer request.

Fault seams: frames WITH a binary payload are the KV wire transport,
so both directions fire the ``serve.transport`` site before the bytes
move, and the send side passes the payload through :func:`faults.tear`
— a ``torn_frame`` spec truncates the package content while the frame
itself stays well-formed, exactly the damage the codec's digest check
must catch on the far side.  Header-only control frames (tick, health)
do not fire: control-plane chaos belongs to ``serve.router`` /
``serve.handoff``.
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Any, Dict, Optional, Tuple

from ... import faults
from ...obs import trace as obs_trace

__all__ = ["RPCError", "send_frame", "recv_frame", "call"]

_LENS = struct.Struct(">II")
#: refuse frames beyond this (a length prefix corrupted into garbage
#: must not make recv try to allocate gigabytes)
MAX_FRAME = 1 << 30


class RPCError(ConnectionError):
    """The peer hung up mid-frame or sent an unparseable frame."""


def send_frame(sock: socket.socket, header: Dict[str, Any],
               payload: bytes = b"") -> None:
    """Write one frame.  Stamps the active trace id into the header
    (when one is active and the caller didn't already), and runs the
    transport fault seam on payload-bearing frames."""
    if "trace" not in header:
        tid = obs_trace.current_trace_id()
        if tid is not None:
            header = dict(header, trace=tid)
    if payload:
        faults.fire("serve.transport", op=header.get("op"),
                    direction="send", nbytes=len(payload))
        payload = faults.tear("serve.transport", payload)
    hj = json.dumps(header, separators=(",", ":")).encode()
    sock.sendall(_LENS.pack(len(hj), len(payload)) + hj + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            raise RPCError(
                f"peer closed mid-frame ({len(buf)}/{n} bytes)")
        buf += chunk
    return bytes(buf)


def recv_frame(sock: socket.socket,
               timeout: Optional[float] = None
               ) -> Tuple[Dict[str, Any], bytes]:
    """Read one frame; returns (header, payload).  ``timeout`` bounds
    the whole read (None = block forever); expiry surfaces as
    ``socket.timeout``."""
    sock.settimeout(timeout)
    try:
        hlen, plen = _LENS.unpack(_recv_exact(sock, _LENS.size))
        if hlen > MAX_FRAME or plen > MAX_FRAME:
            raise RPCError(f"oversized frame ({hlen}+{plen} bytes)")
        try:
            header = json.loads(_recv_exact(sock, hlen).decode())
        except ValueError as e:
            raise RPCError(f"unparseable frame header: {e}") from None
        payload = _recv_exact(sock, plen) if plen else b""
    finally:
        sock.settimeout(None)
    if payload:
        faults.fire("serve.transport", op=header.get("op"),
                    direction="recv", nbytes=len(payload))
    return header, payload


def call(sock: socket.socket, header: Dict[str, Any],
         payload: bytes = b"", *, timeout: Optional[float] = None
         ) -> Tuple[Dict[str, Any], bytes]:
    """One request/response round trip on a connection the caller owns
    exclusively (the supervisor serializes per-worker traffic)."""
    send_frame(sock, header, payload)
    return recv_frame(sock, timeout=timeout)
