"""Worker-process main for the multi-process serving tier (ISSUE 18).

One OS process = one :class:`~singa_tpu.serve.engine.ServeEngine` in a
prefill or decode role, owned by a supervisor
(:mod:`~singa_tpu.serve.net.supervisor`) over a framed local-socket RPC
(:mod:`~singa_tpu.serve.net.rpc`).  The process:

1. pins the virtual-CPU platform (``utils.virtcpu`` — the SAME recipe
   tests/conftest.py uses, so a worker's compiled programs and greedy
   streams are bit-identical to an in-process engine's),
2. connects to the supervisor and says ``hello`` (liveness before the
   expensive part),
3. builds its model from the configured ``module:callable`` builder —
   deterministic construction (seeded init) is what replaces weight
   shipping: every process materializes the same weights — then
   compiles its own engine program set,
4. reports ``ready`` (model key, compile counts, wall time) over the
   control channel, and
5. serves the RPC loop: ``submit`` / ``resubmit`` / ``tick`` /
   ``handoff`` (probe, extract, inject) / ``drain`` / ``health`` /
   ``heartbeat`` (liveness probe) / ``chaos`` (install a worker-side
   fault plan) / ``shutdown``.

Per-process observability: the supervisor points ``SINGA_OBS`` at a
per-worker sink file (``<base>.<worker>``), and every frame's ``trace``
id is re-activated around handling, so one request's events land in
whichever worker served it under ONE trace id — ``tools/obsq trace``
merges the sink files back into a single timeline.

Engine errors never kill the connection: a failed op replies
``{"ok": false, "err": ...}`` and the supervisor decides (re-route,
worker death, or plain rejection).  Only a broken socket ends the
process.
"""

from __future__ import annotations

import argparse
import base64
import contextlib
import importlib
import json
import os
import socket
import sys
import time
from typing import Any, Dict, List, Optional

__all__ = ["main"]


def _load_builder(spec: str):
    """Resolve ``"module:callable"`` to the model-builder function."""
    mod_name, _, fn_name = spec.partition(":")
    if not fn_name:
        raise ValueError(
            f"model builder must be 'module:callable', got {spec!r}")
    return getattr(importlib.import_module(mod_name), fn_name)


def _deadline_rem(req, now: float) -> Optional[float]:
    return None if req.deadline is None else req.deadline - now


class _WorkerServer:
    """The RPC loop around one engine (single-threaded by design: the
    supervisor owns the connection and pipelines at the POOL level —
    concurrency across processes, sequential ops within one)."""

    def __init__(self, engine, name: str, role: str,
                 sock: socket.socket):
        self.engine = engine
        self.name = name
        self.role = role
        self.sock = sock
        #: worker-local rid -> [handle, tokens already reported] — the
        #: delta cursor per tracked request (the supervisor holds the
        #: authoritative mirror; this is just "what changed since the
        #: last tick reply")
        self.tracked: Dict[int, List[Any]] = {}
        self._draining = False

    # -- op handlers -------------------------------------------------------
    def _track(self, handle, already: int) -> None:
        self.tracked[handle.rid] = [handle, already]

    def _collect_delta(self) -> List[dict]:
        out = []
        for rid, slot in list(self.tracked.items()):
            h, last = slot
            toks = h.tokens
            if len(toks) > last or h.done:
                out.append({"rid": rid, "toks": toks[last:],
                            "done": h.done, "state": h.status,
                            "finish_reason": h.finish_reason,
                            "error": h.error, "ttft_s": h.ttft_s})
                slot[1] = len(toks)
                if h.done:
                    del self.tracked[rid]
        return out

    def _ready_prefills(self) -> List[dict]:
        """Parked finished prefills the supervisor can hand off: slot,
        block count, and the prefix chain keys a destination probe
        needs — no KV moves until the supervisor commits to an
        extract."""
        eng = self.engine
        out = []
        for slot, req in eng.running_items():
            if not req.tokens:
                continue
            keys = eng._req_keys(req)[
                :req.prompt.size // eng.pool.block_size]
            out.append({"rid": req.rid, "slot": slot,
                        "n_blocks": eng.pool.mapped_count(slot),
                        "prompt_keys": [k.hex() for k in keys]})
        return out

    def _op_submit(self, hdr: dict) -> dict:
        from ..scheduler import QueueFull
        if self._draining:
            return {"ok": False, "err": "draining"}
        try:
            h = self.engine.submit(
                hdr["prompt"], max_new_tokens=hdr["max_new_tokens"],
                deadline_s=hdr.get("deadline_s"),
                eos_id=hdr.get("eos_id"), trace_id=hdr.get("trace"))
        except QueueFull:
            return {"ok": False, "err": "queue_full"}
        except ValueError as e:
            return {"ok": False, "err": f"value_error: {e}"}
        self._track(h, 0)
        return {"ok": True, "rid": h.rid, "pending": self.engine.pending}

    def _op_resubmit(self, hdr: dict) -> dict:
        if self._draining:
            return {"ok": False, "err": "draining"}
        try:
            h = self.engine.resubmit(
                hdr["prompt"], hdr["tokens"],
                max_new_tokens=hdr["max_new_tokens"],
                deadline_s=hdr.get("deadline_s"),
                eos_id=hdr.get("eos_id"), trace_id=hdr.get("trace"),
                ttft_s=hdr.get("ttft_s"))
        except ValueError as e:
            return {"ok": False, "err": f"value_error: {e}"}
        self._track(h, len(hdr["tokens"]))
        return {"ok": True, "rid": h.rid, "pending": self.engine.pending}

    def _op_tick(self, hdr: dict) -> dict:
        if hdr.get("tick_hint_s") is not None:
            self.engine.tick_hint_s = float(hdr["tick_hint_s"])
        decode = bool(hdr.get("decode", True))
        try:
            delivered = self.engine.step(decode=decode)
        except (RuntimeError, OSError) as e:
            # past the engine's own retry/recovery budget — at the tier
            # level this is a worker death, reported, not raised
            return {"ok": False, "err": f"{type(e).__name__}: {e}"}
        rep = {"ok": True, "delivered": delivered,
               "pending": self.engine.pending,
               "delta": self._collect_delta()}
        if self.role == "prefill" and not decode:
            rep["ready"] = self._ready_prefills()
        return rep

    def _op_handoff(self, hdr: dict, payload: bytes):
        from . import codec
        direction = hdr.get("dir")
        if direction == "probe":
            pkg = codec.probe_package(hdr["prompt"], hdr["n_blocks"],
                                      hdr["prompt_keys"])
            return {"ok": True,
                    "accept": self.engine.can_accept_handoff(pkg)}, b""
        if direction == "extract":
            slot = int(hdr["slot"])
            req = self.engine._running.get(slot)
            if req is None or req.rid != hdr.get("rid"):
                return {"ok": False, "err": "slot_moved"}, b""
            t0 = time.perf_counter()
            try:
                pkg = self.engine.extract_handoff(slot)
                wire = codec.encode_package(pkg, src=self.name)
            except (RuntimeError, OSError, codec.WireError) as e:
                return {"ok": False,
                        "err": f"{type(e).__name__}: {e}"}, b""
            self.tracked.pop(req.rid, None)
            return {"ok": True, "rid": req.rid,
                    "ser_ms": (time.perf_counter() - t0) * 1e3}, wire
        if direction == "inject":
            t0 = time.perf_counter()
            try:
                pkg = codec.decode_package(payload)
            except codec.TornFrame:
                return {"ok": False, "err": "torn_frame"}, b""
            except codec.WireError as e:
                return {"ok": False, "err": f"wire_error: {e}"}, b""
            try:
                injected = self.engine.inject_handoff(pkg)
            except (RuntimeError, OSError) as e:
                return {"ok": False,
                        "err": f"{type(e).__name__}: {e}"}, b""
            if not injected:
                return {"ok": True, "injected": False}, b""
            self._track(pkg.req.handle, len(pkg.req.tokens))
            return {"ok": True, "injected": True, "rid": pkg.req.rid,
                    "deser_ms": (time.perf_counter() - t0) * 1e3}, b""
        return {"ok": False, "err": f"unknown handoff dir {direction!r}"}, \
            b""

    def _op_withdraw(self, hdr: dict) -> dict:
        """Pull one running request out of the engine (slot + blocks
        released, nothing re-queued here) — the supervisor's pre-extract
        failure recovery: the request replays on another worker, so this
        engine just forgets it."""
        slot = int(hdr["slot"])
        req = self.engine._running.get(slot)
        if req is None or (hdr.get("rid") is not None
                           and req.rid != hdr["rid"]):
            return {"ok": False, "err": "slot_moved"}
        self.engine.withdraw(slot)
        self.tracked.pop(req.rid, None)
        return {"ok": True, "rid": req.rid}

    def _op_drain(self, hdr: dict) -> dict:
        """Hand every in-flight request back to the supervisor as host
        state (prompt + tokens so far + budget + remaining deadline) —
        the worker's half of an elastic scale-down.  Running slots are
        withdrawn (blocks released), the queue is emptied, and new
        submissions are refused from here on."""
        self._draining = True
        eng = self.engine
        now = time.monotonic()
        reqs = [eng.withdraw(slot) for slot, _ in eng.running_items()]
        while True:
            r = eng.sched.pop_for_admission()
            if r is None:
                break
            reqs.append(r)
        out = []
        for r in reqs:
            self.tracked.pop(r.rid, None)
            out.append({"rid": r.rid, "prompt": r.prompt.tolist(),
                        "tokens": list(r.tokens),
                        "max_new_tokens": r.max_new_tokens,
                        "deadline_rem_s": _deadline_rem(r, now),
                        "eos_id": r.eos_id, "trace": r.trace_id,
                        "ttft_s": r.ttft_s})
        return {"ok": True, "reqs": out}

    def _op_heartbeat(self, hdr: dict) -> dict:
        """Liveness probe — header-only and engine-free by design: it
        proves the RPC loop itself is being serviced.  The supervisor's
        hang detector keys off THIS (and the per-op deadlines), never
        off process existence — a SIGSTOPped or wedged worker has a
        perfectly live pid and still fails this probe."""
        return {"ok": True, "pid": os.getpid()}

    def _op_chaos(self, hdr: dict) -> dict:
        """Install (or clear) a fault plan inside THIS worker process —
        the chaos campaign's worker-side seam.  ``plan`` is the
        ``SINGA_FAULTS`` syntax (``FaultPlan.parse``); a worker-side
        ``serve.transport`` hang, for instance, wedges the worker's
        payload frames without killing the process, which is exactly
        the hang-≠-crash case the liveness layer exists for.  Empty or
        missing ``plan`` uninstalls."""
        from singa_tpu import faults
        from singa_tpu.faults.plan import FaultPlan
        spec = hdr.get("plan")
        try:
            if spec:
                faults.install(FaultPlan.parse(
                    spec, seed=int(hdr.get("seed", 0))))
            else:
                faults.uninstall()
        except ValueError as e:
            return {"ok": False, "err": f"value_error: {e}"}
        return {"ok": True, "plan": spec or None}

    def _op_health(self, hdr: dict) -> dict:
        m = self.engine.metrics
        rep = {"ok": True, "pending": self.engine.pending,
               "pid": os.getpid(), "role": self.role,
               "snapshot": m.snapshot(),
               "ttft_samples": list(m._ttft.samples),
               "token_samples": list(m._token.samples)}
        counts = getattr(self.engine, "compiled_counts", None)
        if callable(counts):
            # live jit-cache sizes — the campaign's program-set-fixed
            # invariant reads these after every chaos event
            rep["compiles"] = counts()
            hc = getattr(self.engine, "handoff_compiled_count", None)
            if callable(hc):
                rep["handoff_compiles"] = hc()
        return rep

    # -- the loop ----------------------------------------------------------
    def serve(self) -> int:
        from ...obs import trace as obs_trace
        from . import rpc
        while True:
            try:
                hdr, payload = rpc.recv_frame(self.sock)
            except (rpc.RPCError, OSError):
                # supervisor went away: nothing to serve for
                return 0
            op = hdr.get("op")
            tid = hdr.get("trace")
            ctx = (obs_trace.activate(tid) if tid
                   else contextlib.nullcontext())
            with ctx:
                if op == "shutdown":
                    rpc.send_frame(self.sock, {"op": "shutdown",
                                               "ok": True})
                    self.engine.close()
                    return 0
                handler = getattr(self, f"_op_{op}", None)
                if handler is None:
                    rep, pl = {"ok": False,
                               "err": f"unknown op {op!r}"}, b""
                elif op == "handoff":
                    rep, pl = self._op_handoff(hdr, payload)
                else:
                    rep, pl = handler(hdr), b""
                rep["op"] = op
                try:
                    rpc.send_frame(self.sock, rep, pl)
                except OSError:
                    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="serve-tier worker process (spawned by "
                    "singa_tpu.serve.net.supervisor — not a user CLI)")
    ap.add_argument("--sock", required=True,
                    help="AF_UNIX socket path of the supervisor")
    ap.add_argument("--name", required=True)
    ap.add_argument("--role", required=True,
                    choices=("prefill", "decode"))
    ap.add_argument("--config", required=True,
                    help="base64(JSON): model builder + engine kwargs")
    args = ap.parse_args(argv)
    cfg = json.loads(base64.b64decode(args.config).decode())

    # platform pinning BEFORE any backend init (same recipe as
    # tests/conftest.py — bitwise identity with in-process engines
    # requires the same virtual platform)
    from singa_tpu.utils import virtcpu
    if not virtcpu.pin_virtual_cpu(int(cfg.get("devices", 1))):
        print(f"procworker {args.name}: could not pin virtual CPU "
              f"platform", file=sys.stderr)
        return 2

    from singa_tpu.obs import events
    if cfg.get("obs_path"):
        events.configure(path=cfg["obs_path"])

    # connect FIRST: the supervisor sees liveness before paying for the
    # model build + compile, and a build crash surfaces as a closed
    # connection rather than a silent spawn timeout
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.connect(args.sock)
    from . import rpc
    rpc.send_frame(sock, {"op": "hello", "name": args.name,
                          "role": args.role, "pid": os.getpid()})

    t0 = time.perf_counter()
    builder = _load_builder(cfg["model"]["builder"])
    model = builder(**cfg["model"].get("kwargs", {}))
    from singa_tpu.serve import ServeEngine
    engine_kwargs = dict(cfg.get("engine", {}))
    if cfg.get("self_spec_k"):
        # self-speculation rides the same deterministic build: the
        # draft IS the target, so no second model crosses the config
        engine_kwargs["draft_model"] = model
        engine_kwargs["spec_k"] = int(cfg["self_spec_k"])
    engine = ServeEngine(model, **engine_kwargs)
    ready = {"op": "ready", "name": args.name, "ok": True,
             "ready_ms": (time.perf_counter() - t0) * 1e3,
             "pid": os.getpid()}
    try:
        from singa_tpu.autotune import table as autotune_table
        ready["model_key"] = autotune_table.model_key(model)
    except Exception:  # noqa: BLE001 — readiness must not die on a key
        ready["model_key"] = None
    counts = getattr(engine, "compiled_counts", None)
    if callable(counts):
        try:
            ready["compiles"] = counts()
        except Exception:  # noqa: BLE001
            pass
    rpc.send_frame(sock, ready)

    server = _WorkerServer(engine, args.name, args.role, sock)
    try:
        return server.serve()
    finally:
        try:
            sock.close()
        except OSError:
            pass


if __name__ == "__main__":
    sys.exit(main())
