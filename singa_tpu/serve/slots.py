"""Slot-based KV-cache pool for continuous-batching inference.

The arena is the model's own static KV cache (ops/kv_cache.init_cache)
with the batch axis reinterpreted as SLOTS: a fixed
(num_slots, max_len, K, D) buffer pair per layer, allocated once.  A
request is admitted by prefilling its prompt into one slot row and
evicted by returning the slot index to the free list — both are pure
index updates against fixed-shape arrays, so the engine's two compiled
programs serve every admit/evict/decode for the lifetime of the pool
(the same single-compiled-module discipline the Graph/Scheduler layer
enforces for training).

Per-slot ``pos``/``active`` state lives in device arrays (int32/bool
vectors of length num_slots): they are inputs of the decode program, and
admit/evict mutate them with ``.at[slot].set`` — tiny cached index-update
dispatches, never a recompile.  Freed slots are NOT scrubbed: the next
prefill overwrites the slot's entire (max_len) cache row, and decode
masks every slot to its own validity window (cached_sdpa per-row
``limit``), so stale keys beyond a slot's ``pos`` are unreachable.
"""

from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp

__all__ = ["SlotPool"]


class SlotPool:
    """Fixed arena of `num_slots` KV-cache rows of length `max_len`.

    Host side: a free list of slot indices.  Device side: the per-layer
    cache arena plus the per-slot ``pos`` (valid prefix length) and
    ``active`` vectors the decode program consumes.
    """

    def __init__(self, model, num_slots: int, max_len: int, dtype=None):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if max_len < 2:
            raise ValueError(f"max_len must be >= 2, got {max_len}")
        self.num_slots = num_slots
        self.max_len = max_len
        if dtype is None:
            self.caches = model.init_caches(num_slots, max_len)
        else:
            # allocate straight in the serving dtype (e.g. bf16 under a
            # param_dtype cast): eval_shape keeps the full-precision
            # arena abstract, so construction never holds two copies
            import jax
            spec = jax.eval_shape(
                lambda: model.init_caches(num_slots, max_len))
            self.caches = jax.tree.map(
                lambda s: jnp.zeros(s.shape, dtype), spec)
        self.pos = jnp.zeros((num_slots,), jnp.int32)
        self.active = jnp.zeros((num_slots,), bool)
        # LIFO reuse: the most recently freed slot is re-prefilled first
        # (its cache row is hottest in HBM/cache hierarchies)
        self._free: List[int] = list(range(num_slots - 1, -1, -1))

    # -- host-side bookkeeping -------------------------------------------
    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def active_count(self) -> int:
        return self.num_slots - len(self._free)

    def alloc(self) -> Optional[int]:
        """Claim a free slot index, or None when the pool is full (the
        scheduler's signal to queue/reject — backpressure)."""
        return self._free.pop() if self._free else None

    def release(self, slot: int) -> None:
        """Return `slot` to the free list and deactivate it.  The cache
        row is left as-is; the next prefill overwrites it wholesale."""
        if slot in self._free:
            raise ValueError(f"slot {slot} double-freed")
        self.active = self.active.at[slot].set(False)
        self.pos = self.pos.at[slot].set(0)
        self._free.append(slot)

    # -- device-side state transitions -----------------------------------
    def activate(self, slot: int, length: int) -> None:
        """Mark `slot` live with `length` valid cache positions (called
        after its prompt was prefilled into the arena)."""
        self.pos = self.pos.at[slot].set(length)
        self.active = self.active.at[slot].set(True)

    def positions(self):
        """Host copy of per-slot positions (np.ndarray view)."""
        import numpy as np
        return np.asarray(self.pos)
