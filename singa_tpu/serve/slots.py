"""Paged KV-cache arena for continuous-batching inference.

PR 2's ``SlotPool`` gave every request a fixed ``max_len`` cache row, so
a 10-token request paid the same HBM as a 500-token one and a shared
system prompt was re-prefilled from scratch for every tenant.  The
arena is now PAGED (the vLLM design, expressed as fixed-shape XLA
gathers): the per-layer cache is a pool of ``num_blocks`` fixed-size
blocks of ``block_size`` tokens — ``(num_blocks, block_size, K, D)``
buffers — and each request maps the blocks its length actually needs
through a device-resident ``(num_slots, max_blocks)`` int32 **block
table**.  The engine's two compiled programs never see physical block
identities as shapes: prefill/decode gather a request's dense view with
``ops.kv_cache.gather_block_kv`` (a ``jnp.take`` over the table row)
and scatter written positions back with ``scatter_block_kv`` /
``scatter_token_kv``, so admitting, growing, evicting and re-mapping
requests are pure index updates — the same single-compiled-module
discipline the fixed arena had, with memory proportional to live
tokens instead of live slots.

**Prefix-cache sharing** rides the block pool: every FULL prompt block
gets a chain hash key (blake2b over the block's tokens and its
ancestor's key, so a key identifies the whole prefix up to and
including the block).  A new request whose leading prompt blocks are
already resident maps them copy-free (refcount bump, no prefill) and
prefills only the unshared suffix.  Refcounts govern the lifecycle:

* a mapped block has ``ref >= 1`` (one per slot mapping it);
* when the last mapping is released, a KEYED block parks in an LRU
  pool of evictable blocks (content intact — the next request with the
  same prefix reuses it) while an unkeyed block returns to the free
  list immediately;
* allocation takes from the free list first, then evicts the LRU
  evictable block — eviction *asserts* ``ref == 0``, so evicting a
  block while any request references it is impossible by construction.

Physical block 0 is the reserved **null block**: never allocated, it
is the redirect target for unmapped table entries and masked decode
writes.  Its contents are garbage by design — every reader masks cache
positions past its own validity window (``cached_sdpa`` per-row
``limit``), so the null block (like any stale table entry) is
unreachable.

**Memory hierarchy** (ISSUE 17, :mod:`singa_tpu.serve.mem`):
``kv_dtype="int8"`` stores either arena as int8 codes + per-position
f32 scales (:class:`~singa_tpu.ops.kv_cache.QuantKV` — the gather/
scatter primitives quantize/dequantize in-program, so the compiled
program set is unchanged), and a :class:`~singa_tpu.serve.mem.
SpillStore` (``spill=``) turns LRU eviction of a keyed prefix block
into a spill to host RAM: :meth:`_evict_lru` copies the block's exact
device bytes out before reclaiming it, and :meth:`match_prefix`
restores spilled blocks into free physical blocks on the next prefix
hit (both seams fire the ``serve.spill`` injection site; an injected
fault degrades to the pre-spill behavior — the block dies or the
prefix re-prefills — never to a changed stream).
"""

from __future__ import annotations

import hashlib
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from . import mem

__all__ = ["BlockPool"]

#: chain-hash seed of the empty prefix
_ROOT = b"singa-kv-prefix-root"


def _chain_keys(tokens: np.ndarray, n_blocks: int, block_size: int
                ) -> List[bytes]:
    """Keys of the first ``n_blocks`` FULL blocks of ``tokens``; key i
    commits to every token in blocks 0..i, so equal keys mean equal
    whole prefixes (not just equal block contents)."""
    keys, prev = [], _ROOT
    for i in range(n_blocks):
        h = hashlib.blake2b(prev, digest_size=16)
        h.update(tokens[i * block_size:(i + 1) * block_size]
                 .astype("<i4").tobytes())
        prev = h.digest()
        keys.append(prev)
    return keys


class BlockPool:
    """Paged arena of ``num_blocks`` KV blocks behind ``num_slots``
    block-table rows.

    Host side: slot free list, block free list, per-block refcounts,
    the prefix cache (chain key -> block) and the evictable LRU.
    Device side: the per-layer block pools, the ``(num_slots,
    max_blocks)`` block tables, and the per-slot ``pos``/``active``
    vectors the decode program consumes.
    """

    def __init__(self, model, num_slots: int, max_len: int, *,
                 block_size: int = 16, num_blocks: Optional[int] = None,
                 dtype=None, draft_model=None, kv_dtype=None,
                 draft_kv_dtype=None,
                 spill: Optional[mem.SpillStore] = None):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if max_len < 2:
            raise ValueError(f"max_len must be >= 2, got {max_len}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_slots = num_slots
        self.max_len = max_len
        self.block_size = block_size
        self.max_blocks = -(-max_len // block_size)
        if num_blocks is None:
            # capacity parity with the old fixed arena (+ null block):
            # every slot can hold a full-length request at once
            num_blocks = num_slots * self.max_blocks + 1
        if num_blocks < self.max_blocks + 1:
            raise ValueError(
                f"num_blocks ({num_blocks}) must cover the largest "
                f"request plus the null block (>= {self.max_blocks + 1} "
                f"for max_len {max_len} at block_size {block_size})")
        self.num_blocks = num_blocks
        # the memory-hierarchy knobs (serve/mem.py): per-arena storage
        # format (None = full precision, "int8" = QuantKV codes +
        # scales) — the draft arena inherits the target's format unless
        # overridden, so a quantized engine quantizes both by default
        # while the referee configuration (int8 proposer, f32 target)
        # stays expressible via draft_kv_dtype="int8" alone
        self.kv_dtype = mem.normalize_kv_dtype(kv_dtype)
        self.draft_kv_dtype = (self.kv_dtype if draft_kv_dtype is None
                               else mem.normalize_kv_dtype(draft_kv_dtype))
        if self.kv_dtype == "int8":
            # int8 arena: codes + scales replace the float pool (the
            # dtype= serving-precision override is moot — scales are
            # f32 by contract, codes are int8)
            self.caches = mem.quant_arena(model, num_blocks, block_size)
        elif dtype is None:
            self.caches = model.init_caches(num_blocks, block_size)
        else:
            # allocate straight in the serving dtype (e.g. bf16 under a
            # param_dtype cast): eval_shape keeps the full-precision
            # arena abstract, so construction never holds two copies
            import jax
            spec = jax.eval_shape(
                lambda: model.init_caches(num_blocks, block_size))
            self.caches = jax.tree.map(
                lambda s: jnp.zeros(s.shape, dtype), spec)
        # speculative decoding (serve/spec.py): the DRAFT model's KV
        # blocks ride the SAME block tables — draft caches are a second
        # per-layer pool with identical (num_blocks, block_size) leading
        # dims (draft layer/head/dim shapes differ freely), so every
        # host-side mapping decision (admit, grow, evict, prefix share,
        # preempt, handoff) covers both arenas with one index update.
        # A shared full prompt block therefore shares its draft KV too:
        # the spec prefill writes both, and block content is a
        # deterministic function of the chain-keyed prefix either way.
        self.draft_model = draft_model
        if draft_model is None:
            self.draft_caches = None
        elif self.draft_kv_dtype == "int8":
            self.draft_caches = mem.quant_arena(draft_model, num_blocks,
                                                block_size)
        elif dtype is None:
            self.draft_caches = draft_model.init_caches(num_blocks,
                                                        block_size)
        else:
            # the serving-dtype override applies to BOTH arenas: decode
            # and verify are weight/KV-read bound, and a full-precision
            # draft arena would double the draft's KV traffic (and,
            # under self-speculation, let draft and target argmaxes
            # diverge by reading different-precision KV)
            import jax
            spec = jax.eval_shape(
                lambda: draft_model.init_caches(num_blocks, block_size))
            self.draft_caches = jax.tree.map(
                lambda s: jnp.zeros(s.shape, dtype), spec)
        self.tables = jnp.zeros((num_slots, self.max_blocks), jnp.int32)
        self.pos = jnp.zeros((num_slots,), jnp.int32)
        self.active = jnp.zeros((num_slots,), bool)
        # LIFO reuse: the most recently freed slot/block is re-used
        # first (hottest in the HBM/cache hierarchy)
        self._free_slots: List[int] = list(range(num_slots - 1, -1, -1))
        self._free_blocks: List[int] = list(range(num_blocks - 1, 0, -1))
        self._mapped: List[List[int]] = [[] for _ in range(num_slots)]
        self.ref = np.zeros((num_blocks,), np.int64)
        self._key_of: Dict[int, bytes] = {}     # block -> chain key
        self._block_of: Dict[bytes, int] = {}   # chain key -> block
        # refcount-0 keyed blocks, oldest first (eviction order)
        self._lru: "OrderedDict[int, None]" = OrderedDict()
        # spill tier (serve/mem.py): evicted keyed blocks land here
        # instead of dying; the engine wires the three callbacks after
        # construction (metrics for spill/prefetch accounting, incident
        # plumbing for injected serve.spill faults)
        self.spill = spill
        self.on_spill = None        # callable(n_blocks)
        self.on_prefetch = None     # callable(n_blocks, wait_ms)
        self.on_spill_fault = None  # callable(op, exc)
        #: bytes ONE physical block occupies across every arena leaf
        #: (target + draft, codes + scales) — the honest per-block HBM
        #: footprint behind blocks_in_use_bytes
        self.block_bytes = mem.arena_block_bytes(self.caches,
                                                 self.draft_caches)

    # -- slot bookkeeping -------------------------------------------------
    @property
    def free_count(self) -> int:
        return len(self._free_slots)

    @property
    def active_count(self) -> int:
        return self.num_slots - len(self._free_slots)

    def alloc_slot(self) -> Optional[int]:
        """Claim a free block-table row, or None when every row is live
        (the scheduler's signal to keep the request queued)."""
        return self._free_slots.pop() if self._free_slots else None

    # -- block accounting -------------------------------------------------
    @property
    def available_blocks(self) -> int:
        """Blocks an allocation could obtain right now: the free list
        plus the evictable (refcount-0) prefix blocks."""
        return len(self._free_blocks) + len(self._lru)

    @property
    def blocks_in_use(self) -> int:
        """Blocks currently referenced by at least one mapped slot."""
        return int((self.ref > 0).sum())

    @property
    def blocks_in_use_bytes(self) -> int:
        """HBM bytes those blocks pin across BOTH arenas (target +
        draft, int8 codes AND f32 scale tensors) — blocks alone
        under-report a quantized or speculative arena's footprint."""
        return self.blocks_in_use * self.block_bytes

    def mapped_count(self, slot: int) -> int:
        return len(self._mapped[slot])

    def mapped_blocks(self, slot: int) -> List[int]:
        """The slot's physical block ids in logical order (a copy) —
        what a disaggregated KV handoff transfers: the source engine
        gathers these blocks' contents and the destination pool maps
        the same logical sequence onto its own physical blocks."""
        return list(self._mapped[slot])

    def _evict_lru(self) -> int:
        block, _ = self._lru.popitem(last=False)
        # the invariant the prefix cache stands on: only a block no
        # request references may ever be reclaimed
        assert self.ref[block] == 0, \
            f"evicting block {block} with refcount {self.ref[block]}"
        key = self._key_of.pop(block, None)
        if key is not None and self._block_of.get(key) == block:
            del self._block_of[key]
            if self.spill is not None:
                self._spill_block(key, block)
        return block

    # -- spill tier (serve/mem.py) ----------------------------------------
    def _spill_block(self, key: bytes, block: int) -> None:
        """Spill-write seam: copy the evicted keyed block's exact
        device bytes into the host store BEFORE the arena reclaims the
        physical block.  An injected ``serve.spill`` fault here skips
        the spill — the block dies exactly as it did before the spill
        tier existed (a prefix-cache miss later, never a changed
        stream)."""
        from .. import faults
        try:
            faults.fire("serve.spill", op="spill", block=block)
        except (RuntimeError, OSError) as e:
            if self.on_spill_fault is not None:
                self.on_spill_fault("spill", e)
            return
        self.spill.put(key, mem.read_block(self.caches,
                                           self.draft_caches, block))
        if self.on_spill is not None:
            self.on_spill(1)

    def _stage_restore(self, key: bytes) -> Optional[Tuple[int, dict]]:
        """Prefetch-read seam: claim an available physical block — a
        free one, else by evicting the coldest refcount-0 LRU block
        (which itself spills: a SWAP of a cold prefix for the hot one
        being requested, never touching a referenced block) — and pop
        the spilled payload for it.  Returns ``(block, payload)``, or
        None on a store miss / no claimable block / injected fault
        (all of which degrade to a plain prefix miss: the suffix
        prefills normally).  Consuming free-or-LRU is exactly the
        budget :meth:`probe_prefix`'s conservative feasibility math
        (spilled = miss) already charged for this block's fresh
        allocation, so admission accounting is unchanged.  The device
        write is deferred to :meth:`_commit_restores` so an admission
        restoring several blocks pays ONE batched write."""
        if self.spill is None or key not in self.spill \
                or not (self._free_blocks or self._lru):
            return None
        from .. import faults
        try:
            faults.fire("serve.spill", op="prefetch")
        except (RuntimeError, OSError) as e:
            if self.on_spill_fault is not None:
                self.on_spill_fault("prefetch", e)
            return None
        payload = self.spill.get(key)
        if (payload["draft"] is None) != (self.draft_caches is None):
            return None  # arena shape changed under the store
        self.spill.pop(key)
        block = (self._free_blocks.pop() if self._free_blocks
                 else self._evict_lru())
        return block, payload

    def _commit_restores(self, restores: List[Tuple[bytes, int, dict]]
                         ) -> None:
        """Land an admission's staged restores: one fancy-indexed
        device write per arena leaf (see :func:`mem.write_blocks`),
        then key the blocks resident.  The writes ride JAX's async
        dispatch — the host enqueues the copies and returns;
        ``wait_ms`` measures the host-side restore orchestration the
        admission actually waited."""
        t0 = time.perf_counter()
        self.caches, self.draft_caches = mem.write_blocks(
            self.caches, self.draft_caches,
            [b for _, b, _ in restores], [p for _, _, p in restores])
        for key, block, _ in restores:
            self._key_of[block] = key
            self._block_of[key] = block
        if self.on_prefetch is not None:
            self.on_prefetch(len(restores),
                             (time.perf_counter() - t0) * 1e3)

    def alloc_blocks(self, n: int) -> Optional[List[int]]:
        """Claim ``n`` physical blocks (all-or-nothing), evicting LRU
        prefix blocks as needed.  None when fewer than ``n`` are
        obtainable — the caller's cue to defer admission or preempt."""
        if self.available_blocks < n:
            return None
        out = []
        for _ in range(n):
            out.append(self._free_blocks.pop() if self._free_blocks
                       else self._evict_lru())
        return out

    def free_blocks(self, blocks: List[int]) -> None:
        """Return unmapped, unkeyed blocks straight to the free list
        (the cleanup path of an admission that failed between
        allocation and mapping)."""
        for b in blocks:
            assert self.ref[b] == 0 and b not in self._key_of
            self._free_blocks.append(b)

    def unref_shared(self, blocks: List[int]) -> None:
        """Drop the references :meth:`match_prefix` took, without a
        slot mapping to release through (the cleanup path of an
        admission that failed before :meth:`map_slot`)."""
        for b in blocks:
            assert self.ref[b] > 0
            self.ref[b] -= 1
            if self.ref[b] == 0:
                self._lru[b] = None
                self._lru.move_to_end(b)

    def release_slot_row(self, slot: int) -> None:
        """Hand back an UNMAPPED slot row (failed admission) — the
        block-side cleanup happened through :meth:`unref_shared` /
        :meth:`free_blocks`."""
        assert not self._mapped[slot]
        if slot in self._free_slots:
            raise ValueError(f"slot {slot} double-freed")
        self._free_slots.append(slot)

    # -- prefix cache -----------------------------------------------------
    def prefix_keys(self, prompt: np.ndarray, n_blocks: int
                    ) -> List[bytes]:
        """Chain keys of ``prompt``'s first ``n_blocks`` full blocks —
        exposed so the engine can memoize them per request (they depend
        only on the immutable prompt) and pass them back via ``keys=``
        instead of re-hashing on every admission probe."""
        return _chain_keys(prompt, n_blocks, self.block_size)

    def probe_prefix(self, prompt: np.ndarray, limit_blocks: int,
                     keys: Optional[List[bytes]] = None
                     ) -> Tuple[int, int]:
        """How many leading full blocks of ``prompt`` are resident, and
        how many of those currently sit in the evictable LRU
        (side-effect free — the admission-feasibility check).  The LRU
        count matters because claiming those shared blocks REMOVES them
        from :attr:`available_blocks`: an admission is feasible only
        when ``available_blocks - n_in_lru`` covers the fresh blocks it
        must still allocate."""
        if keys is None:
            keys = _chain_keys(prompt, limit_blocks, self.block_size)
        n = n_lru = 0
        for key in keys[:limit_blocks]:
            block = self._block_of.get(key)
            if block is None:
                break
            n += 1
            if self.ref[block] == 0:
                n_lru += 1
        return n, n_lru

    def match_prefix(self, prompt: np.ndarray, limit_blocks: int,
                     keys: Optional[List[bytes]] = None
                     ) -> Tuple[int, List[int]]:
        """Claim the longest resident chain of leading full prompt
        blocks: each matched block's refcount is bumped (reactivating
        it out of the evictable LRU).  A key that misses residency but
        hits the spill tier is PREFETCHED into a free physical block
        and the chain continues — the restored block consumes exactly
        the one free block the conservative :meth:`probe_prefix`
        feasibility math already budgeted for its fresh allocation, so
        admission accounting is unchanged.  Returns (n_shared, block
        ids)."""
        if keys is None:
            keys = _chain_keys(prompt, limit_blocks, self.block_size)
        ids: List[int] = []
        restores: List[Tuple[bytes, int, dict]] = []
        for key in keys[:limit_blocks]:
            block = self._block_of.get(key)
            if block is None:
                staged = self._stage_restore(key)
                if staged is None:
                    break
                block, payload = staged
                restores.append((key, block, payload))
            if self.ref[block] == 0:
                self._lru.pop(block, None)
            self.ref[block] += 1
            ids.append(block)
        if restores:
            self._commit_restores(restores)
        return len(ids), ids

    def register_prefix(self, prompt: np.ndarray, slot: int,
                        n_blocks: int,
                        keys: Optional[List[bytes]] = None) -> None:
        """Key the first ``n_blocks`` (full, just-prefilled prompt)
        blocks of ``slot`` so later requests with the same prefix can
        map them.  A key already mapping another resident block is
        re-pointed here (the old holder keeps serving its refs but
        loses shareability — content is identical either way)."""
        if keys is None:
            keys = _chain_keys(prompt, n_blocks, self.block_size)
        row = self._mapped[slot]
        for i, key in enumerate(keys[:n_blocks]):
            block = row[i]
            if self._key_of.get(block) == key:
                continue                     # matched share, already keyed
            old = self._block_of.get(key)
            if old is not None and old != block:
                del self._key_of[old]
                if old in self._lru:         # keyless + unreferenced:
                    self._lru.pop(old)       # nothing can find it again
                    self._free_blocks.append(old)
            self._block_of[key] = block
            self._key_of[block] = key

    # -- slot mapping ------------------------------------------------------
    def _sync_table_row(self, slot: int) -> None:
        row = np.zeros((self.max_blocks,), np.int32)
        mapped = self._mapped[slot]
        row[:len(mapped)] = mapped
        self.tables = self.tables.at[slot].set(jnp.asarray(row))

    def map_slot(self, slot: int, blocks: List[int]) -> None:
        """Install ``blocks`` (shared prefix + freshly allocated, in
        logical order) as the slot's block table.  Shared blocks arrive
        with their refcount already bumped by :meth:`match_prefix`;
        fresh ones are claimed here."""
        assert not self._mapped[slot], f"slot {slot} already mapped"
        if len(blocks) > self.max_blocks:
            raise ValueError(
                f"{len(blocks)} blocks exceed max_blocks "
                f"({self.max_blocks})")
        self._mapped[slot] = list(blocks)
        for b in blocks:
            if self.ref[b] == 0:
                self.ref[b] = 1
        self._sync_table_row(slot)

    def append_block(self, slot: int, block: int) -> None:
        """Decode-time growth: one more block for a slot whose next
        token crosses a block boundary."""
        if len(self._mapped[slot]) >= self.max_blocks:
            raise ValueError(f"slot {slot} already at max_blocks")
        self._mapped[slot].append(block)
        self.ref[block] = 1
        self._sync_table_row(slot)

    def release(self, slot: int) -> None:
        """Return the slot row to the free list and drop one reference
        from every block it mapped: keyed blocks park in the evictable
        LRU (content intact for the next prefix hit), unkeyed ones are
        freed.  Device-side cache rows are never scrubbed — stale
        blocks are unreachable past every reader's validity window."""
        if slot in self._free_slots:
            raise ValueError(f"slot {slot} double-freed")
        for b in self._mapped[slot]:
            assert self.ref[b] > 0
            self.ref[b] -= 1
            if self.ref[b] == 0:
                if b in self._key_of:
                    self._lru[b] = None
                    self._lru.move_to_end(b)
                else:
                    self._free_blocks.append(b)
        self._mapped[slot] = []
        self.active = self.active.at[slot].set(False)
        self.pos = self.pos.at[slot].set(0)
        self._free_slots.append(slot)

    # -- device-side state transitions -----------------------------------
    def activate(self, slot: int, length: int) -> None:
        """Mark ``slot`` live with ``length`` valid cache positions
        (called after its prompt chunks were prefilled into its
        blocks)."""
        self.pos = self.pos.at[slot].set(length)
        self.active = self.active.at[slot].set(True)

    def positions(self):
        """Host copy of per-slot positions (np.ndarray view)."""
        return np.asarray(self.pos)
