"""Serving telemetry: queue/slot gauges, admission counters, latency
histograms — all through the shared ``obs.events`` layer, so a single
``SINGA_OBS=/path.jsonl`` env var captures training AND serving events
in one stream.

Metric names (documented in docs/serving.md):

==========================  =========  ==================================
name                        kind       meaning
==========================  =========  ==================================
``serve.submitted``         counter    requests accepted by submit()
``serve.admitted``          counter    first prefill into a slot (recovery
                                       re-prefills count under
                                       ``serve.recoveries``)
``serve.rejected``          counter    refused at submit (queue full)
``serve.evicted``           counter    left the system — a slot vacated
                                       (``eos``/``length``/``deadline``)
                                       or a queued request dropped at
                                       its deadline or shed under
                                       overload (``reason`` attr)
``serve.retries``           counter    one transient dispatch failure
                                       retried with backoff (``site``)
``serve.quarantined``       counter    a request the engine gave up on
                                       (failed handle status)
``serve.recoveries``        counter    arena rebuild + re-prefill of
                                       in-flight requests
``serve.preempted``         counter    a running request released its
                                       blocks to an exhausted pool and
                                       re-queued (replayed later,
                                       stream unchanged)
``serve.prefix_hits``       counter    an admission mapped >= 1 resident
                                       shared-prefix block copy-free
``serve.prefix_hit_tokens`` counter    prompt tokens whose prefill was
                                       SKIPPED via the prefix cache
``serve.queue_depth``       gauge      waiting requests, after each step
``serve.active_slots``      gauge      live slots, after each step
``serve.blocks_in_use``     gauge      referenced KV blocks, after each
                                       step (the paged-arena footprint)
``serve.blocks_in_use_bytes``  gauge   HBM bytes those blocks pin —
                                       target + draft arenas, int8
                                       codes AND f32 scale tensors
                                       (block counts alone under-report
                                       a quantized/speculative arena)
``serve.spilled_blocks``    counter    evicted prefix blocks whose
                                       bytes landed in the host-RAM
                                       spill tier instead of dying
``serve.prefetch_hits``     counter    spilled blocks restored into the
                                       arena on a prefix hit (one per
                                       restored block)
``serve.prefetch_wait_ms``  histogram  host-side restore orchestration
                                       per prefetched block (the copy
                                       itself rides JAX async dispatch)
``serve.step``              span       one engine step (host wall clock)
``serve.prefill``           span       one prefill dispatch (+ fetch)
``serve.decode``            span       one decode dispatch (+ fetch)
``serve.verify``            span       one speculative verify round
                                       (draft propose-k + target
                                       verify in ONE dispatch + fetch;
                                       ``k`` attr)
``serve.spec_proposed``     counter    draft tokens proposed this round
                                       (k per active slot)
``serve.spec_accepted``     counter    proposals the target's own
                                       greedy picks confirmed
``serve.spec_fallbacks``    counter    verify rounds that fell back to
                                       plain decode (``serve.verify``
                                       fault past retries)
``serve.accept_rate``       histogram  per-(slot, round) accepted / k
``serve.token``             counter    one token delivered to a request
                                       (prefill first token, decode
                                       tick, recovery/preemption replay
                                       — tokens/s is derivable from the
                                       trace by counting these)
``serve.ttft_ms``           histogram  submit → first token
``serve.token_ms``          histogram  per generated token, decode path
==========================  =========  ==================================

Counters/gauges cost one attribute check when no sink is configured.
Latency aggregation is PER ENGINE: each ServeMetrics owns its own
histogram state (``snapshot()`` reads it), so two engines in one
process never reset or pollute each other's percentiles; the emitted
``serve.ttft_ms``/``serve.token_ms`` sink lines keep the documented
names (the global ``events.histogram_summary`` view then spans every
engine — by design for a whole-process dashboard).

Trace attribution (ISSUE 11): the engine activates the request's
``obs.trace`` context around each per-request section, so every line
above that is about ONE request carries its trace id — and the same
events are noted into the engine's :class:`~singa_tpu.obs.flight.
FlightRecorder` ring (pass ``flight=``), which is what an incident
dump's timeline is made of.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ..obs import events
from ..obs import flight as obs_flight
# per-engine aggregation state reuses the events-layer histogram
# implementation (exact totals + bounded deterministic sample ring)
from ..obs.events import _Hist

__all__ = ["ServeMetrics"]


class ServeMetrics:
    """Thin per-engine facade: exact local totals (for snapshots/tests)
    plus pass-through emission to the shared obs sink and (when given)
    the engine's flight-recorder ring."""

    def __init__(self, flight: Optional[obs_flight.FlightRecorder] = None):
        self.flight = flight
        self.submitted = 0
        self.admitted = 0
        self.rejected = 0
        self.evicted: Dict[str, int] = {}
        self.retries: Dict[str, int] = {}
        self.quarantined = 0
        self.recoveries = 0
        self.preempted = 0
        self.prefix_hits = 0
        self.prefix_hit_tokens = 0
        self.steps = 0
        # KV memory hierarchy (ISSUE 17): spill-tier pressure counters
        self.spilled_blocks = 0
        self.prefetch_hits = 0
        self.prefetch_wait_ms = 0.0
        # speculative decoding (ISSUE 13): per-(slot, round) accounting
        # for the accept rate and the tokens-per-dispatch headline —
        # slot_dispatches counts per-slot participations in a decode OR
        # verify dispatch (a plain tick is the 1-token case), so
        # tokens_per_dispatch = slot_dispatch_tokens / slot_dispatches
        # is comparable across spec and plain engines
        self.spec_rounds = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.spec_fallbacks = 0
        self.slot_dispatches = 0
        self.slot_dispatch_tokens = 0
        self._accept = _Hist()
        self._ttft = _Hist()
        self._token = _Hist()

    def _note(self, kind: str, name: str, **attrs) -> None:
        """Mirror one emission into the engine's flight ring (in-memory
        only; the active trace id is stamped by the recorder)."""
        if self.flight is not None:
            self.flight.note(kind, name, **attrs)

    # -- request lifecycle ------------------------------------------------
    def on_submit(self) -> None:
        self.submitted += 1
        events.counter("serve.submitted", 1)
        self._note("counter", "serve.submitted")

    def on_reject(self) -> None:
        self.rejected += 1
        events.counter("serve.rejected", 1)
        self._note("counter", "serve.rejected")

    def on_admit(self) -> None:
        self.admitted += 1
        events.counter("serve.admitted", 1)
        self._note("counter", "serve.admitted")

    def on_evict(self, reason: str) -> None:
        self.evicted[reason] = self.evicted.get(reason, 0) + 1
        events.counter("serve.evicted", 1, reason=reason)
        self._note("counter", "serve.evicted", reason=reason)

    # -- resilience (ISSUE 4) ---------------------------------------------
    def on_retry(self, site: str) -> None:
        self.retries[site] = self.retries.get(site, 0) + 1
        events.counter("serve.retries", 1, site=site)
        self._note("counter", "serve.retries", site=site)

    def on_quarantine(self) -> None:
        self.quarantined += 1
        events.counter("serve.quarantined", 1)
        self._note("counter", "serve.quarantined")

    def on_recover(self, inflight: int) -> None:
        self.recoveries += 1
        events.counter("serve.recoveries", 1, inflight=inflight)
        self._note("counter", "serve.recoveries", inflight=inflight)

    def on_preempt(self) -> None:
        self.preempted += 1
        events.counter("serve.preempted", 1)
        self._note("counter", "serve.preempted")

    # -- paged arena / prefix cache (ISSUE 6) ------------------------------
    def on_prefix_hit(self, tokens: int) -> None:
        self.prefix_hits += 1
        self.prefix_hit_tokens += tokens
        events.counter("serve.prefix_hits", 1)
        events.counter("serve.prefix_hit_tokens", tokens)
        self._note("counter", "serve.prefix_hits", tokens=tokens)

    # -- KV memory hierarchy / spill tier (ISSUE 17) -----------------------
    def on_spill(self, blocks: int) -> None:
        """``blocks`` evicted prefix blocks spilled to host RAM instead
        of dying (their next prefix hit restores them copy-wise)."""
        self.spilled_blocks += blocks
        events.counter("serve.spilled_blocks", blocks)
        self._note("counter", "serve.spilled_blocks", blocks=blocks)

    def on_prefetch(self, blocks: int, wait_ms: float) -> None:
        """``blocks`` spilled block(s) restored on one prefix hit (the
        pool fires this once per restored block); ``wait_ms`` is the
        host-side restore orchestration time (the device copy itself
        is async-dispatched)."""
        self.prefetch_hits += blocks
        self.prefetch_wait_ms += wait_ms
        events.counter("serve.prefetch_hits", 1, blocks=blocks)
        events.histogram("serve.prefetch_wait_ms", wait_ms)
        self._note("counter", "serve.prefetch_hits", blocks=blocks,
                   wait_ms=round(wait_ms, 3))

    # -- speculative decoding (ISSUE 13) -----------------------------------
    def on_spec_round(self, proposed: int, accepted: int) -> None:
        """One (slot, verify round): ``proposed`` = k draft tokens,
        ``accepted`` = how many of them the target's own greedy picks
        confirmed (the round still delivers accepted + 1 tokens — the
        correction/bonus pick is the target's, not the draft's)."""
        self.spec_rounds += 1
        self.spec_proposed += proposed
        self.spec_accepted += accepted
        rate = accepted / proposed if proposed else 0.0
        self._accept.observe(rate)
        events.counter("serve.spec_proposed", proposed)
        events.counter("serve.spec_accepted", accepted)
        events.histogram("serve.accept_rate", rate)
        self._note("counter", "serve.spec_accepted", accepted=accepted,
                   proposed=proposed)

    def on_spec_fallback(self) -> None:
        """A verify round died past retries and this tick ran plain
        decode instead — stream unchanged, accept rate pays later."""
        self.spec_fallbacks += 1
        events.counter("serve.spec_fallbacks", 1)
        self._note("counter", "serve.spec_fallbacks")

    def on_slot_dispatch(self, tokens: int) -> None:
        """One slot's share of one decode/verify dispatch, yielding
        ``tokens`` delivered tokens — the denominator/numerator pair of
        the ``tokens_per_dispatch`` headline."""
        self.slot_dispatches += 1
        self.slot_dispatch_tokens += tokens

    @property
    def accept_rate(self) -> Optional[float]:
        """Overall accepted / proposed (None before any verify round)."""
        if not self.spec_proposed:
            return None
        return self.spec_accepted / self.spec_proposed

    @property
    def tokens_per_dispatch(self) -> Optional[float]:
        """Delivered tokens per per-slot dispatch participation (None
        before any decode/verify tick; exactly 1.0 for a plain
        engine)."""
        if not self.slot_dispatches:
            return None
        return self.slot_dispatch_tokens / self.slot_dispatches

    # -- latency / delivery ------------------------------------------------
    def on_first_token(self, ttft_s: float) -> None:
        self._ttft.observe(ttft_s * 1e3)
        events.histogram("serve.ttft_ms", ttft_s * 1e3)
        self._note("hist", "serve.ttft_ms", value=ttft_s * 1e3)

    def on_token(self, latency_s: float) -> None:
        self._token.observe(latency_s * 1e3)
        events.histogram("serve.token_ms", latency_s * 1e3)

    def on_deliver(self, rid: int, n: int) -> None:
        """One token handed to a request (any path: prefill first
        token, decode tick, recovery/preemption replay) — the
        trace-countable delivery event tokens/s derives from."""
        events.counter("serve.token", 1, rid=rid, n=n)
        self._note("counter", "serve.token", rid=rid, n=n)

    # -- per-step levels ---------------------------------------------------
    def on_step(self, queue_depth: int, active_slots: int,
                blocks_in_use: int = 0,
                blocks_in_use_bytes: int = 0) -> None:
        self.steps += 1
        events.gauge("serve.queue_depth", queue_depth)
        events.gauge("serve.active_slots", active_slots)
        events.gauge("serve.blocks_in_use", blocks_in_use)
        events.gauge("serve.blocks_in_use_bytes", blocks_in_use_bytes)
        self._note("gauge", "serve.step", queue_depth=queue_depth,
                   active_slots=active_slots,
                   blocks_in_use=blocks_in_use,
                   blocks_in_use_bytes=blocks_in_use_bytes)

    def snapshot(self) -> Dict[str, Any]:
        """Exact totals + THIS engine's latency summaries (None until
        observed)."""
        return {
            "submitted": self.submitted, "admitted": self.admitted,
            "rejected": self.rejected, "evicted": dict(self.evicted),
            "retries": dict(self.retries),
            "quarantined": self.quarantined,
            "recoveries": self.recoveries,
            "preempted": self.preempted,
            "prefix_hits": self.prefix_hits,
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "steps": self.steps,
            "spilled_blocks": self.spilled_blocks,
            "prefetch_hits": self.prefetch_hits,
            "prefetch_wait_ms": self.prefetch_wait_ms,
            "spec_rounds": self.spec_rounds,
            "spec_proposed": self.spec_proposed,
            "spec_accepted": self.spec_accepted,
            "spec_fallbacks": self.spec_fallbacks,
            "slot_dispatches": self.slot_dispatches,
            "slot_dispatch_tokens": self.slot_dispatch_tokens,
            "accept_rate": self.accept_rate,
            "tokens_per_dispatch": self.tokens_per_dispatch,
            "accept_rate_hist": self._accept.summary(),
            "ttft_ms": self._ttft.summary(),
            "token_ms": self._token.summary(),
        }
