"""Proto-enum parity module (reference: core.proto's DataType/DeviceType
messages; SURVEY.md section 2.2 row 10 — "keep minimal proto for
dtype/device enums only; Python dataclasses elsewhere").

The reference lineage serializes dtype/device kinds as protobuf enums;
the TPU-native equivalent keeps the *numbering contract* (so serialized
configs interoperate) without a protoc dependency: plain IntEnums plus
converters to the framework's neutral currency (numpy dtypes / jax
dtypes).  sonnx's wire codec (sonnx/proto.py) carries ONNX's own enum
space; this module is the singa-side one.
"""

from __future__ import annotations

import enum

import jax.numpy as jnp
import numpy as np

__all__ = ["DataType", "DeviceType", "LangType",
           "to_np_dtype", "from_np_dtype"]


class DataType(enum.IntEnum):
    """Mirrors the lineage's core.proto DataType numbering; kBfloat16 is
    the TPU-native addition (appended, so existing numbers are stable)."""

    kFloat32 = 0
    kFloat16 = 1
    kInt = 2
    kChar = 3
    kDouble = 4
    kUChar = 5
    kBfloat16 = 6
    kInt64 = 7
    kUnknown = 10


class DeviceType(enum.IntEnum):
    """Lineage device kinds; kTpu is the north-star addition
    (BASELINE.json:5 — "add a singa::TpuDevice alongside CppCPU/CudaGPU")."""

    kCpp = 0
    kCuda = 1
    kOpencl = 2
    kTpu = 3


class LangType(enum.IntEnum):
    """Kernel-language tag the lineage attaches to device ops; kXla is the
    TPU-native addition (math dispatches to XLA instead of hand kernels)."""

    kCpp = 0
    kCuda = 1
    kOpencl = 2
    kXla = 3


_TO_NP = {
    DataType.kFloat32: np.dtype(np.float32),
    DataType.kFloat16: np.dtype(np.float16),
    DataType.kInt: np.dtype(np.int32),
    DataType.kChar: np.dtype(np.int8),
    DataType.kDouble: np.dtype(np.float64),
    DataType.kUChar: np.dtype(np.uint8),
    DataType.kBfloat16: np.dtype(jnp.bfloat16),
    DataType.kInt64: np.dtype(np.int64),
}
_FROM_NP = {v: k for k, v in _TO_NP.items()}


def to_np_dtype(dt: DataType) -> np.dtype:
    try:
        return _TO_NP[DataType(dt)]
    except KeyError:
        raise ValueError(f"no numpy dtype for {dt!r}") from None


def from_np_dtype(dtype) -> DataType:
    return _FROM_NP.get(np.dtype(dtype), DataType.kUnknown)
