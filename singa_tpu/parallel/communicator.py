"""Communicator — XLA collectives over ICI/DCN (capability parity with
the reference's NCCL Communicator: allreduce / fused / fp16 / sparsified
gradient reduction, BASELINE.json:5).

All collectives here are *in-graph*: they are jnp/lax ops that only take
effect inside shard_map/pmap traces, where they lower to XLA
all-reduce / all-gather HLO executed by libtpu over ICI.  Fusion parity:
XLA's all-reduce combiner merges the per-tensor reduces into large
buckets, which is the reference's hand-written fused-bucket path done by
the compiler.  Compressed allreduce (bf16) mirrors
`backward_and_update_half`; fixed-K sparsified allreduce mirrors the
top-K path (SURVEY.md §7.3 item 4: fixed-K all-gather formulation,
because shape-dynamic top-K is hostile to XLA).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp

__all__ = ["axis_bound", "allreduce", "allreduce_grads", "allgather",
           "reduce_scatter", "ppermute", "broadcast", "axis_index",
           "axis_size", "barrier", "quantized_allreduce",
           "ef_quantized_allreduce", "int8_ring_wire_bytes",
           "f32_ring_wire_bytes"]


def axis_bound(axis: str) -> bool:
    """True when `axis` is a live mapped axis (inside shard_map/pmap)."""
    try:
        jax.lax.axis_index(axis)
        return True
    except NameError:
        return False


def _staged(collective: str, x, axis: str, **attrs) -> None:
    """Per-collective staging hook: the ``comm.collective`` injection
    site (host-side, so an injected error surfaces at trace time like a
    failed collective launch) plus the payload counter."""
    from .. import faults
    faults.fire("comm.collective", collective=collective, axis=axis)
    _payload_counter(collective, x, axis, **attrs)


def _payload_counter(collective: str, x, axis: str, **attrs) -> None:
    """Emit a ``comm.<collective>.bytes`` counter for a staged
    collective.

    Collectives are in-graph ops, so this fires at TRACE time (once per
    compile, not per execution) and records the payload the wire will
    carry on every step — the quantity bandwidth accounting needs.
    Shapes/dtypes are concrete on tracers, so no device work happens."""
    from ..obs import events
    if not events.enabled():
        return
    try:
        nbytes = sum(int(l.size) * l.dtype.itemsize
                     for l in jax.tree.leaves(x)
                     if hasattr(l, "size") and hasattr(l, "dtype"))
    except Exception:  # exotic pytree leaves must never break a trace
        return
    events.counter(f"comm.{collective}.bytes", nbytes, axis=axis, **attrs)


# ---------------------------------------------------------------------------
# wire-byte accounting (the obs ``comm.wire_bytes.*`` counters)
# ---------------------------------------------------------------------------

def _ring_chunk(n: int, world: int, block: int) -> int:
    """Per-rank chunk length of the int8 ring over `n` elements: the
    padded layout both `_ring_int8_allreduce` and the byte model use —
    ONE definition so the counters can never drift from the kernel."""
    C = -(-n // world)
    C += (-C) % block
    return C


def f32_ring_wire_bytes(n: int, world: int) -> int:
    """Per-participant ring-allreduce wire bytes of an f32 payload of
    `n` elements: ``2(W-1)/W x 4n`` — the f32-equivalent every
    compressed variant is compared against (same model as the cost
    gate's COST005)."""
    if world <= 1:
        return 0
    return int(round(2.0 * (world - 1) / world * n * 4))


def int8_ring_wire_bytes(n: int, world: int, block: int = 256) -> int:
    """Per-participant wire bytes of one int8 ring RS+AG over `n`
    elements — the deterministic trace-time model behind the
    ``comm.wire_bytes.compressed`` counter and ``bench.py --quantized``:
    (W-1) reduce-scatter permute hops of C int8 bytes, a ring
    all-gather moving another (W-1)·C int8 bytes, plus the per-block
    absmax consensus (one f32 pmax of W·C/block scales, ring factor
    2(W-1)/W).  C is the padded per-rank chunk (`_ring_chunk`)."""
    if world <= 1:
        return 0
    C = _ring_chunk(n, world, block)
    payload = 2 * (world - 1) * C                     # int8: 1 B/elem
    consensus = int(round(2.0 * (world - 1) / world
                          * world * (C // block) * 4))
    return payload + consensus


def _emit_wire_counters(n_elems: int, axis: str, mode: str,
                        block: int = 256) -> None:
    """Emit the ``comm.wire_bytes.compressed`` / ``.f32_equiv``
    counter pair for one gradient-sync call (trace time — shapes and
    the axis size are static, so this is free at execution).  Every
    sync reports BOTH numbers so a record always shows what the wire
    actually carried next to what f32 would have cost."""
    from ..obs import events
    if not events.enabled():
        return
    W = jax.lax.axis_size(axis)
    f32_eq = f32_ring_wire_bytes(n_elems, W)
    if mode == "int8_ring":
        compressed = int8_ring_wire_bytes(n_elems, W, block)
    elif mode == "bf16":
        compressed = int(round(2.0 * (W - 1) / W * n_elems * 2))
    else:
        compressed = f32_eq
    events.counter("comm.wire_bytes.compressed", compressed,
                   axis=axis, mode=mode)
    events.counter("comm.wire_bytes.f32_equiv", f32_eq,
                   axis=axis, mode=mode)


def axis_index(axis: str):
    return jax.lax.axis_index(axis)


def axis_size(axis: str) -> int:
    return jax.lax.axis_size(axis)


def allreduce(x, axis: str = "data", op: str = "mean"):
    if not axis_bound(axis):
        return x
    _staged("allreduce", x, axis, op=op)
    if op == "mean":
        return jax.lax.pmean(x, axis)
    if op == "sum":
        return jax.lax.psum(x, axis)
    if op == "max":
        return jax.lax.pmax(x, axis)
    if op == "min":
        return jax.lax.pmin(x, axis)
    raise ValueError(f"unknown reduce op {op}")


def allgather(x, axis: str = "data", tiled: bool = False):
    if not axis_bound(axis):
        return x
    _staged("allgather", x, axis)
    return jax.lax.all_gather(x, axis, tiled=tiled)


def reduce_scatter(x, axis: str = "data", scatter_dimension: int = 0):
    if not axis_bound(axis):
        return x
    _staged("reduce_scatter", x, axis)
    return jax.lax.psum_scatter(x, axis, scatter_dimension=scatter_dimension,
                                tiled=True)


def ppermute(x, axis: str, perm):
    if not axis_bound(axis):
        return x
    _staged("ppermute", x, axis)
    return jax.lax.ppermute(x, axis, perm)


def broadcast(x, axis: str = "data", src: int = 0):
    """Replicate rank-src's value via a distance-doubling ppermute tree.

    ceil(log2(W)) ring hops: after hop k, every rank whose offset from
    src (mod W) is < 2^(k+1) holds the value.  Each hop is a full
    permutation (one-to-many ppermute is rejected by JAX), and each
    rank moves the payload log2(W) times total — the native
    collective-permute lowering, vs. the old select+psum workaround
    that ran a full f32 all-reduce over masked zeros."""
    if not axis_bound(axis):
        return x
    W = jax.lax.axis_size(axis)
    if W == 1:
        return x
    _staged("broadcast", x, axis)
    d = (jax.lax.axis_index(axis) - src) % W  # offset from src, traced
    val = x
    step = 1
    while step < W:
        perm = [(i, (i + step) % W) for i in range(W)]
        recv = jax.lax.ppermute(val, axis, perm)
        # the sender (offset d-step) holds a valid value iff d-step < step
        use = (d >= step) & (d < 2 * step)
        val = jax.tree.map(lambda r, v: jnp.where(use, r, v), recv, val)
        step *= 2
    return val


def barrier(axis: str = "data"):
    if axis_bound(axis):
        jax.lax.psum(jnp.ones(()), axis)


# ---------------------------------------------------------------------------
# gradient allreduce with the reference Communicator's variants
# ---------------------------------------------------------------------------

def allreduce_grads(grads: Dict[str, jnp.ndarray], axis: str = "data",
                    compress_dtype=None,
                    topk_ratio: float = 0.0) -> Dict[str, jnp.ndarray]:
    """Mean-allreduce a dict of gradients over `axis`.

    compress_dtype: cast to (e.g.) bf16 pre-reduce — halves ICI bytes
    (reference: fp16 allreduce).  topk_ratio>0: fixed-K sparsified
    exchange (reference: sparsified allreduce)."""
    if not axis_bound(axis):
        return grads
    _staged("allreduce_grads",
            [g for g in grads.values() if g is not None], axis,
            tensors=len(grads),
            compress=None if compress_dtype is None
            else str(compress_dtype),
            topk_ratio=topk_ratio or 0.0)
    n_elems = sum(int(g.size) for g in grads.values() if g is not None)
    mode = "f32"
    if compress_dtype == "int8_ring":
        mode = "int8_ring"
    elif compress_dtype is not None and not _is_int8(compress_dtype):
        try:
            if jnp.dtype(compress_dtype).itemsize == 2:
                mode = "bf16"
        except TypeError:
            pass
    _emit_wire_counters(n_elems, axis, mode)
    out = {}
    for name, g in grads.items():
        if g is None:
            out[name] = None
            continue
        if topk_ratio and topk_ratio > 0.0 and g.size > 1024:
            out[name] = _topk_allreduce(g, axis, topk_ratio)
        elif compress_dtype == "int8_ring":
            # true byte reduction: int8 payloads on the wire (ring RS+AG)
            out[name] = quantized_allreduce(g, axis, wire="int8")
        elif _is_int8(compress_dtype):
            # accuracy-first variant: int8 codes summed in int32 (int32
            # wire; bounds error at s/2, does not reduce bytes)
            out[name] = quantized_allreduce(g, axis)
        elif compress_dtype is not None and g.dtype != compress_dtype:
            out[name] = jax.lax.pmean(g.astype(compress_dtype), axis).astype(g.dtype)
        else:
            out[name] = jax.lax.pmean(g, axis)
    return out


def _is_int8(compress_dtype) -> bool:
    """Accept "int8", np.int8, jnp.int8 — a plain astype to an int dtype
    would truncate gradients to zero, so int8 must route to the
    quantized path regardless of spelling."""
    if compress_dtype is None:
        return False
    if isinstance(compress_dtype, str):
        return compress_dtype == "int8"
    try:
        return jnp.dtype(compress_dtype) == jnp.dtype(jnp.int8)
    except TypeError:
        return False


def quantized_allreduce(x, axis: str = "data", block: int = 256,
                        wire: str = "int32"):
    """Int8 blockwise-quantized mean-allreduce (EQuARX-style,
    PAPERS.md:5 — the TPU-idiomatic substitute for the reference's
    compressed allreduce). Per-block f32 scales are agreed via a pmax so
    every replica quantizes onto the same shared grid s = absmax/127.

    wire="int32" (default): quantize once, psum the int8 codes in int32.
    The int32 accumulation *bounds the error* at |err| <= s/2 per
    element regardless of world size — but the wire payload is int32,
    so this variant reduces quantization error, NOT bytes on the wire.

    wire="int8": true byte reduction — a ring reduce-scatter of int8
    payloads (requantized each hop onto a widened shared grid) followed
    by an int8 all-gather, the EQuARX shape. Every hop's ppermute and
    the final all-gather move 1 byte/element over ICI (4x fewer than
    f32); worst-case error grows O(world) from the per-hop requantize.
    """
    if wire not in ("int32", "int8"):
        raise ValueError(f"wire must be 'int32' or 'int8', got {wire!r}")
    if not axis_bound(axis):
        return x
    _staged("quantized_allreduce", x, axis, wire=wire)
    if wire == "int8":
        return _ring_int8_allreduce(x, axis, block)
    orig_shape, orig_dtype = x.shape, x.dtype
    flat = x.astype(jnp.float32).reshape(-1)
    pad = (-flat.size) % block
    if pad:
        flat = jnp.pad(flat, (0, pad))
    blocks = flat.reshape(-1, block)
    absmax = jnp.max(jnp.abs(blocks), axis=1, keepdims=True)
    # consensus scale per block: every replica must use the same grid
    absmax = jax.lax.pmax(absmax, axis)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(blocks / scale), -127, 127).astype(jnp.int8)
    total = jax.lax.psum(q.astype(jnp.int32), axis)
    w = jax.lax.axis_size(axis)
    out = total.astype(jnp.float32) * scale / w
    out = out.reshape(-1)
    if pad:
        out = out[:-pad]
    return out.reshape(orig_shape).astype(orig_dtype)


def _ring_int8_allreduce(x, axis: str, block: int, with_error: bool = False):
    """Ring reduce-scatter + all-gather with int8 wire payloads.

    Each of the W-1 reduce-scatter hops requantizes the running partial
    sum onto grid s*(t+1) (so magnitudes up to (t+1)*absmax never clip)
    and ppermutes the int8 codes one rank forward; the final chunk sums
    are requantized onto grid s*W and all-gathered as int8. All scales
    are consensus values (pmax), so no scale traffic accompanies the
    payload hops.

    Determinism contract: the decode is BITWISE deterministic — the
    block layout is a fixed reshape (rank-major chunks, `block`-element
    blocks in array order), every hop's requantize grid is the fixed
    widening s*(t+1) of the consensus scale (pmax — identical on every
    rank), and the ring schedule is the static unrolled forward
    permutation.  Same inputs on the same topology therefore always
    produce the same synced result, on every rank (the all-gathered
    codes ARE the result; no rank-local arithmetic follows them).

    ``with_error=True`` additionally returns the caller's LOCAL
    quantization error on the hop-0 grid — ``x - dequantize(quantize(x,
    s))`` — the residual error-feedback accumulates (what this rank's
    contribution lost to the wire this round)."""
    W = jax.lax.axis_size(axis)
    r = jax.lax.axis_index(axis)
    orig_shape, orig_dtype = x.shape, x.dtype
    flat = x.astype(jnp.float32).reshape(-1)
    n = flat.size
    # per-chunk length: multiple of `block`, chunks cover the padded array
    C = _ring_chunk(n, W, block)
    pad = W * C - n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    chunks = flat.reshape(W, C)
    blocks = chunks.reshape(W, C // block, block)
    absmax = jax.lax.pmax(jnp.max(jnp.abs(blocks), axis=2), axis)  # (W, C/b)
    s = jnp.where(absmax > 0, absmax / 127.0, 1.0)                 # (W, C/b)

    def grid_for(c, mult):
        # per-element grid for chunk c widened by `mult`
        sc = jnp.take(s, c, axis=0)                                # (C/b,)
        return jnp.repeat(sc * mult, block)                        # (C,)

    fwd = [(i, (i + 1) % W) for i in range(W)]
    partial = jnp.take(chunks, r, axis=0)          # value-domain f32, (C,)
    for t in range(W - 1):
        c_send = (r - t) % W
        g_send = grid_for(c_send, float(t + 1))
        q = jnp.clip(jnp.round(partial / g_send), -127, 127).astype(jnp.int8)
        q_recv = jax.lax.ppermute(q, axis, fwd)    # int8 on the wire
        c_recv = (r - t - 1) % W
        partial = (q_recv.astype(jnp.float32) * grid_for(c_recv, float(t + 1))
                   + jnp.take(chunks, c_recv, axis=0))
    c_own = (r + 1) % W
    g_final = grid_for(c_own, float(W))
    q_final = jnp.clip(jnp.round(partial / g_final), -127, 127).astype(jnp.int8)
    all_q = jax.lax.all_gather(q_final, axis)      # (W, C) int8 on the wire
    # rank (c-1) % W owns chunk c after the ring; undo the rotation
    order = jnp.asarray([(c - 1) % W for c in range(W)])
    codes = jnp.take(all_q, order, axis=0).astype(jnp.float32)     # (W, C)
    # mean = sum/W = codes * (s*W)/W = codes * s
    vals = codes.reshape(W, C // block, block) * s[:, :, None]
    out = vals.reshape(-1)
    if pad:
        out = out[:-pad]
    out = out.reshape(orig_shape).astype(orig_dtype)
    if not with_error:
        return out
    # local quantization error on the hop-0 consensus grid: what THIS
    # rank's contribution lost when it first hit the wire.  Computed
    # from the same scales `s` (no extra consensus traffic) in the same
    # fixed block order, so it is as deterministic as the decode.
    grid = jnp.repeat(s.reshape(-1), block)                        # (W*C,)
    q_local = jnp.clip(jnp.round(flat / grid), -127, 127)
    err = flat - q_local * grid
    if pad:
        err = err[:-pad]
    return out, err.reshape(orig_shape)


def ef_quantized_allreduce(x, residual, axis: str = "data",
                           block: int = 256):
    """Int8-ring mean-allreduce with error feedback — the production
    gradient-sync kernel behind ``DistOpt(compression="int8_ring")``.

    Returns ``(mean, new_residual)``: the f32 residual (this rank's
    accumulated quantization error) is added to ``x`` BEFORE
    quantization, and refilled after decode with what the compensated
    payload lost on the hop-0 grid — so error the int8 wire cannot
    carry this step is re-applied on a later step instead of being
    dropped (EF-SGD; without it, gradient components persistently
    smaller than half the quantization grid are truncated to zero on
    every step and their parameters never move).  Outside a mapped axis
    this is the identity: ``(x, residual)`` unchanged.

    Deterministic per the `_ring_int8_allreduce` contract; the residual
    update shares the decode's consensus scales and block order."""
    if not axis_bound(axis):
        return x, residual
    _staged("quantized_allreduce", x, axis, wire="int8", ef=True)
    _emit_wire_counters(int(x.size), axis, "int8_ring", block)
    comp = x.astype(jnp.float32) + residual
    out, err = _ring_int8_allreduce(comp, axis, block, with_error=True)
    return out.astype(x.dtype), err.astype(jnp.float32)


def _topk_allreduce(g, axis: str, ratio: float):
    """Fixed-K sparsified allreduce: each replica contributes its top-K
    magnitude entries; exchanged via all-gather; scatter-add to dense.
    K is static (trace-time) so shapes stay XLA-friendly."""
    flat = g.reshape(-1)
    k = max(1, int(flat.size * ratio))
    _, idx = jax.lax.top_k(jnp.abs(flat), k)
    vals = jnp.take(flat, idx)
    w = jax.lax.axis_size(axis)
    all_vals = jax.lax.all_gather(vals, axis)   # (W, k)
    all_idx = jax.lax.all_gather(idx, axis)     # (W, k)
    dense = jnp.zeros_like(flat).at[all_idx.reshape(-1)].add(
        all_vals.reshape(-1) / w)
    return dense.reshape(g.shape)
