"""Communicator — XLA collectives over ICI/DCN (capability parity with
the reference's NCCL Communicator: allreduce / fused / fp16 / sparsified
gradient reduction, BASELINE.json:5).

All collectives here are *in-graph*: they are jnp/lax ops that only take
effect inside shard_map/pmap traces, where they lower to XLA
all-reduce / all-gather HLO executed by libtpu over ICI.  Fusion parity:
XLA's all-reduce combiner merges the per-tensor reduces into large
buckets, which is the reference's hand-written fused-bucket path done by
the compiler.  Compressed allreduce (bf16) mirrors
`backward_and_update_half`; fixed-K sparsified allreduce mirrors the
top-K path (SURVEY.md §7.3 item 4: fixed-K all-gather formulation,
because shape-dynamic top-K is hostile to XLA).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp

__all__ = ["axis_bound", "allreduce", "allreduce_grads", "allgather",
           "reduce_scatter", "ppermute", "broadcast", "axis_index",
           "axis_size", "barrier", "quantized_allreduce"]


def axis_bound(axis: str) -> bool:
    """True when `axis` is a live mapped axis (inside shard_map/pmap)."""
    try:
        jax.lax.axis_index(axis)
        return True
    except NameError:
        return False


def axis_index(axis: str):
    return jax.lax.axis_index(axis)


def axis_size(axis: str) -> int:
    return jax.lax.axis_size(axis)


def allreduce(x, axis: str = "data", op: str = "mean"):
    if not axis_bound(axis):
        return x
    if op == "mean":
        return jax.lax.pmean(x, axis)
    if op == "sum":
        return jax.lax.psum(x, axis)
    if op == "max":
        return jax.lax.pmax(x, axis)
    if op == "min":
        return jax.lax.pmin(x, axis)
    raise ValueError(f"unknown reduce op {op}")


def allgather(x, axis: str = "data", tiled: bool = False):
    if not axis_bound(axis):
        return x
    return jax.lax.all_gather(x, axis, tiled=tiled)


def reduce_scatter(x, axis: str = "data", scatter_dimension: int = 0):
    if not axis_bound(axis):
        return x
    return jax.lax.psum_scatter(x, axis, scatter_dimension=scatter_dimension,
                                tiled=True)


def ppermute(x, axis: str, perm):
    if not axis_bound(axis):
        return x
    return jax.lax.ppermute(x, axis, perm)


def broadcast(x, axis: str = "data", src: int = 0):
    """Replicate rank-src's value: implemented as select + psum."""
    if not axis_bound(axis):
        return x
    idx = jax.lax.axis_index(axis)
    masked = jnp.where(idx == src, x, jnp.zeros_like(x))
    return jax.lax.psum(masked, axis)


def barrier(axis: str = "data"):
    if axis_bound(axis):
        jax.lax.psum(jnp.ones(()), axis)


# ---------------------------------------------------------------------------
# gradient allreduce with the reference Communicator's variants
# ---------------------------------------------------------------------------

def allreduce_grads(grads: Dict[str, jnp.ndarray], axis: str = "data",
                    compress_dtype=None,
                    topk_ratio: float = 0.0) -> Dict[str, jnp.ndarray]:
    """Mean-allreduce a dict of gradients over `axis`.

    compress_dtype: cast to (e.g.) bf16 pre-reduce — halves ICI bytes
    (reference: fp16 allreduce).  topk_ratio>0: fixed-K sparsified
    exchange (reference: sparsified allreduce)."""
    if not axis_bound(axis):
        return grads
    out = {}
    for name, g in grads.items():
        if g is None:
            out[name] = None
            continue
        if topk_ratio and topk_ratio > 0.0 and g.size > 1024:
            out[name] = _topk_allreduce(g, axis, topk_ratio)
        elif _is_int8(compress_dtype):
            out[name] = quantized_allreduce(g, axis)
        elif compress_dtype is not None and g.dtype != compress_dtype:
            out[name] = jax.lax.pmean(g.astype(compress_dtype), axis).astype(g.dtype)
        else:
            out[name] = jax.lax.pmean(g, axis)
    return out


def _is_int8(compress_dtype) -> bool:
    """Accept "int8", np.int8, jnp.int8 — a plain astype to an int dtype
    would truncate gradients to zero, so int8 must route to the
    quantized path regardless of spelling."""
    if compress_dtype is None:
        return False
    if isinstance(compress_dtype, str):
        return compress_dtype == "int8"
    try:
        return jnp.dtype(compress_dtype) == jnp.dtype(jnp.int8)
    except TypeError:
        return False


def quantized_allreduce(x, axis: str = "data", block: int = 256):
    """Int8 blockwise-quantized mean-allreduce (EQuARX-style,
    PAPERS.md:5 — the TPU-idiomatic substitute for the reference's
    compressed allreduce): per-block f32 scales are agreed via a pmax
    so every replica quantizes onto the same grid, int8 payloads are
    summed in int32 over ICI (4x fewer bytes than f32), and the result
    is rescaled. Error is bounded by the shared scale: |err| <= s/2
    per element."""
    if not axis_bound(axis):
        return x
    orig_shape, orig_dtype = x.shape, x.dtype
    flat = x.astype(jnp.float32).reshape(-1)
    pad = (-flat.size) % block
    if pad:
        flat = jnp.pad(flat, (0, pad))
    blocks = flat.reshape(-1, block)
    absmax = jnp.max(jnp.abs(blocks), axis=1, keepdims=True)
    # consensus scale per block: every replica must use the same grid
    absmax = jax.lax.pmax(absmax, axis)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(blocks / scale), -127, 127).astype(jnp.int8)
    total = jax.lax.psum(q.astype(jnp.int32), axis)
    w = jax.lax.axis_size(axis)
    out = total.astype(jnp.float32) * scale / w
    out = out.reshape(-1)
    if pad:
        out = out[:-pad]
    return out.reshape(orig_shape).astype(orig_dtype)


def _topk_allreduce(g, axis: str, ratio: float):
    """Fixed-K sparsified allreduce: each replica contributes its top-K
    magnitude entries; exchanged via all-gather; scatter-add to dense.
    K is static (trace-time) so shapes stay XLA-friendly."""
    flat = g.reshape(-1)
    k = max(1, int(flat.size * ratio))
    _, idx = jax.lax.top_k(jnp.abs(flat), k)
    vals = jnp.take(flat, idx)
    w = jax.lax.axis_size(axis)
    all_vals = jax.lax.all_gather(vals, axis)   # (W, k)
    all_idx = jax.lax.all_gather(idx, axis)     # (W, k)
    dense = jnp.zeros_like(flat).at[all_idx.reshape(-1)].add(
        all_vals.reshape(-1) / w)
    return dense.reshape(g.shape)
