"""Pipeline parallelism over the 'pipe' mesh axis (GPipe schedule,
SPMD formulation).

Not a reference capability (SURVEY.md §2.3: the reference's only
strategy is DP) — this is the TPU-native extension that makes the
'pipe' axis advertised in parallel.mesh real.  Design follows the
collective-pipelining recipe: run under shard_map with each 'pipe' rank
holding ONE stage's parameters; every schedule tick each rank applies
its stage and ships the activation to the next rank with a single
`lax.ppermute` hop over ICI; `lax.scan` drives the n_micro + S - 1
ticks.  Because `ppermute`'s transpose is the reverse permute and scan
differentiates, `jax.grad` of the pipelined loss IS the backward
pipeline (reverse schedule) — no hand-written bwd pass.

Stages must share one parameter structure (scan-over-layers style);
stage params are stacked on a leading axis sharded over 'pipe'.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from . import mesh as mesh_mod

__all__ = ["gpipe", "stack_stage_params", "pipeline_mesh"]


def pipeline_mesh(n_stages: int, data: int = 1):
    axes = {}
    if data > 1:
        axes["data"] = data
    axes["pipe"] = n_stages
    return mesh_mod.make_mesh(axes)


def stack_stage_params(per_stage_params):
    """[stage0_tree, stage1_tree, ...] -> one tree with a leading stage
    axis (shard it over 'pipe' via P('pipe'))."""
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *per_stage_params)


def gpipe(stage_fn: Callable, n_micro: int, axis: str = "pipe"):
    """Build the per-shard body of a GPipe pipeline.

    stage_fn(stage_params, x) -> y  must map activations of one
    microbatch through one stage, preserving shape (classic pipeline
    constraint; project in/out around the pipeline).

    Returns body(stage_params, x_micro) for use inside shard_map, where
      * stage_params: this rank's stage weights (leading stage axis
        already consumed by the 'pipe' in_spec),
      * x_micro: (n_micro, mb, ...) microbatched input, replicated over
        `axis`,
    and the result is (n_micro, mb, ...) — the last stage's outputs,
    replicated back so every rank returns the same value.
    """

    def body(stage_params, x_micro):
        # the 'pipe' in_spec leaves a leading stage axis of length 1;
        # anything else means stacked stages != pipe axis size and a[0]
        # would silently drop stages
        for leaf in jax.tree.leaves(stage_params):
            if leaf.shape[0] != 1:
                raise ValueError(
                    f"stacked stage count x pipe axis mismatch: per-rank "
                    f"leading stage axis is {leaf.shape[0]}, expected 1 — "
                    f"stack exactly axis_size('{axis}') stages")
        stage_params = jax.tree.map(lambda a: a[0], stage_params)
        S = jax.lax.axis_size(axis)
        r = jax.lax.axis_index(axis)
        n_ticks = n_micro + S - 1
        fwd = [(i, (i + 1) % S) for i in range(S)]
        mb_shape = x_micro.shape[1:]
        out0 = jnp.zeros((n_micro,) + mb_shape, x_micro.dtype)
        buf0 = jnp.zeros(mb_shape, x_micro.dtype)

        def tick(carry, t):
            buf, outs = carry
            # stage 0 injects microbatch t (clamped; masked-off later)
            inject = jax.lax.dynamic_index_in_dim(
                x_micro, jnp.clip(t, 0, n_micro - 1), axis=0, keepdims=False)
            x_in = jnp.where(r == 0, inject, buf)
            y = stage_fn(stage_params, x_in)
            # my microbatch index this tick; stage r works on t - r
            mb_idx = t - r
            live = jnp.logical_and(mb_idx >= 0, mb_idx < n_micro)
            # bubble ticks must not pollute grads: zero the activation
            y = jnp.where(live, y, jnp.zeros_like(y))
            # last stage records its finished microbatch
            outs = _record(outs, y, mb_idx, r, S, live)
            buf = jax.lax.ppermute(y, axis, fwd)
            return (buf, outs), None

        (_, outs), _ = jax.lax.scan(tick, (buf0, out0), jnp.arange(n_ticks))
        # replicate the last stage's collected outputs to every rank
        outs = jax.lax.psum(
            jnp.where(r == S - 1, outs, jnp.zeros_like(outs)), axis)
        return outs

    return body


def _record(outs, y, mb_idx, r, S, live):
    take = jnp.logical_and(r == S - 1, live)
    updated = jax.lax.dynamic_update_index_in_dim(
        outs, y, jnp.clip(mb_idx, 0, outs.shape[0] - 1), axis=0)
    return jnp.where(take, updated, outs)
