"""Multi-host bootstrap — the reference Communicator's MPI rank setup,
TPU-native (SURVEY.md §2.4, §3.3: "process bootstrap via JAX/PJRT
distributed runtime (coordinator + process_index) instead of MPI").

One process per host; `init_distributed()` connects the process to the
coordination service, after which `jax.devices()` is the GLOBAL device
list and every mesh built from it spans the pod.  On CPU the collective
backend is Gloo (selected automatically) so the same N-process path is
testable with no TPU: tests/test_multiproc.py launches N local
processes and asserts DP-allreduce ≡ single-process big-batch.

Environment-driven (reference: `mpirun` env), explicit args win:

    SINGA_COORDINATOR   host:port of process 0   (or COORDINATOR_ADDRESS)
    SINGA_NUM_PROCS     world size               (or num_processes arg)
    SINGA_PROC_ID       this process's rank      (or process_id arg)

On Cloud TPU pods all three are discovered automatically by JAX and
`init_distributed()` can be called with no arguments at all.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence

__all__ = ["init_distributed", "finalize_distributed", "is_initialized",
           "global_mesh", "local_batch", "assert_same_across_processes"]

_initialized = False


def _env(name: str, *alts: str) -> Optional[str]:
    for k in (name,) + alts:
        v = os.environ.get(k)
        if v:
            return v
    return None


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     local_device_ids: Optional[Sequence[int]] = None) -> int:
    """Connect this process to the JAX distributed runtime.

    Returns the process index.  Safe to call when already initialized
    (returns the current index).  Single-process fallback: with no
    coordinator configured anywhere, this is a no-op returning 0 — so
    example scripts can call it unconditionally.
    """
    global _initialized
    import jax

    if _initialized:
        return jax.process_index()

    coordinator_address = coordinator_address or _env(
        "SINGA_COORDINATOR", "COORDINATOR_ADDRESS")
    if num_processes is None:
        v = _env("SINGA_NUM_PROCS", "NUM_PROCESSES")
        num_processes = int(v) if v else None
    if process_id is None:
        v = _env("SINGA_PROC_ID", "PROCESS_ID")
        process_id = int(v) if v else None

    # TPU pod auto-detect: only a real multi-worker topology counts
    # (single-host images may export TPU_WORKER_HOSTNAMES=localhost)
    hostnames = _env("TPU_WORKER_HOSTNAMES") or ""
    tpu_pod = ("," in hostnames) or _env("MEGASCALE_COORDINATOR_ADDRESS")
    if coordinator_address is None and num_processes is None and not tpu_pod:
        return 0  # single-process mode

    if jax._src.xla_bridge.backends_are_initialized():
        import warnings
        warnings.warn(
            "init_distributed() called after the JAX backend was already "
            "initialized; multi-process bootstrap skipped. Call it before "
            "any jax.devices()/computation.", stacklevel=2)
        return jax.process_index()

    # CPU multi-process collectives need the Gloo backend; harmless to
    # request before backend init, ignored by the TPU plugin.
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu") or \
            jax.config.jax_platforms == "cpu":
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass

    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id,
                               local_device_ids=local_device_ids)
    _initialized = True
    return jax.process_index()


def finalize_distributed() -> None:
    """Disconnect from the coordination service (reference:
    Communicator destructor / MPI_Finalize)."""
    global _initialized
    if not _initialized:
        return
    import jax

    jax.distributed.shutdown()
    _initialized = False


def is_initialized() -> bool:
    return _initialized


def global_mesh(axes: Dict[str, int]):
    """Mesh over the GLOBAL device list (all processes). Axis sizes as
    in `make_mesh`; the product must not exceed the global device
    count."""
    import jax

    from . import mesh as mesh_mod
    return mesh_mod.make_mesh(axes, jax.devices())


def local_batch(global_batch, axis_size: Optional[int] = None):
    """Slice this process's contiguous shard of a host-global batch
    (axis 0).  The reference's per-rank data partitioning; use when each
    host loads the full batch and must feed only its share."""
    import jax
    import numpy as np

    n = axis_size or jax.process_count()
    b = np.asarray(global_batch)
    if b.shape[0] % n:
        raise ValueError(f"batch {b.shape[0]} not divisible by {n} processes")
    per = b.shape[0] // n
    i = jax.process_index()
    return b[i * per:(i + 1) * per]


def assert_same_across_processes(value: float, tol: float = 0.0) -> None:
    """Debug guard: every process must see the same scalar (e.g. the
    replicated loss).  Uses an in-graph collective so it works under
    any backend."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    if jax.process_count() == 1:
        return
    from jax import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()), ("_chk",))
    mx = jax.jit(shard_map(lambda x: jax.lax.pmax(x, "_chk"), mesh=mesh,
                           in_specs=P(), out_specs=P()))(
        jnp.float32(value))
    mn = jax.jit(shard_map(lambda x: jax.lax.pmin(x, "_chk"), mesh=mesh,
                           in_specs=P(), out_specs=P()))(
        jnp.float32(value))
    if float(mx) - float(mn) > tol:
        raise AssertionError(
            f"cross-process divergence: max={float(mx)} min={float(mn)}")
