"""singa_tpu.parallel — device meshes, collectives, parallelism
strategies (DP today; TP/FSDP/SP via mesh-axis changes — SURVEY.md §2.3)
and the multi-host bootstrap (SURVEY.md §2.4)."""

from . import mesh
from . import communicator
from . import distributed
from .mesh import (make_mesh, set_mesh, current_mesh, data_parallel_mesh,
                   mesh_shape)
from .distributed import (init_distributed, finalize_distributed,
                          global_mesh, local_batch)

__all__ = ["mesh", "communicator", "distributed", "make_mesh", "set_mesh",
           "current_mesh", "data_parallel_mesh", "mesh_shape",
           "init_distributed", "finalize_distributed", "global_mesh",
           "local_batch"]
