"""singa_tpu.parallel — device meshes, collectives, and parallelism
strategies (DP today; TP/FSDP/SP via mesh-axis changes — SURVEY.md §2.3).
"""

from . import mesh
from . import communicator
from .mesh import (make_mesh, set_mesh, current_mesh, data_parallel_mesh,
                   mesh_shape)

__all__ = ["mesh", "communicator", "make_mesh", "set_mesh", "current_mesh",
           "data_parallel_mesh", "mesh_shape"]
