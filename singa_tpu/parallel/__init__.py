"""singa_tpu.parallel — device meshes, collectives, parallelism
strategies (DP today; TP/FSDP/SP via mesh-axis changes — SURVEY.md §2.3)
and the multi-host bootstrap (SURVEY.md §2.4)."""

from . import mesh
from . import communicator
from . import distributed
from . import pipeline
from . import planner
from .mesh import (make_mesh, set_mesh, current_mesh, data_parallel_mesh,
                   mesh_shape)
from .distributed import (init_distributed, finalize_distributed,
                          global_mesh, local_batch)
from .planner import plan_train_step

__all__ = ["mesh", "communicator", "distributed", "pipeline", "planner",
           "make_mesh", "set_mesh", "current_mesh", "data_parallel_mesh",
           "mesh_shape", "init_distributed", "finalize_distributed",
           "global_mesh", "local_batch", "plan_train_step"]
