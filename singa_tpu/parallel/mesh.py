"""Device-mesh management (the ICI/DCN topology handle).

The reference's Communicator bootstraps ranks via MPI (BASELINE.json:5);
our equivalent is a ``jax.sharding.Mesh`` over PJRT devices — intra-slice
axes ride ICI, the inter-slice axis rides DCN.  All parallelism in
singa_tpu is expressed as mesh axes:

    'data'  — data parallel (the reference's only strategy)
    'model' — tensor parallel (stretch: Llama-3-8B, BASELINE.json:11)
    'seq'   — sequence/context parallel (ring attention)
    'pipe'  — pipeline stages (GPipe schedule: parallel.pipeline.gpipe)
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_mesh", "set_mesh", "current_mesh", "data_parallel_mesh",
           "mesh_shape", "P", "NamedSharding", "named_sharding",
           "process_index", "process_count", "local_devices",
           "set_data_axis", "current_data_axis"]

_current_mesh: Optional[Mesh] = None
_data_axis: str = "data"


def set_data_axis(name: str) -> None:
    """Install the batch-sharding axis name (the executor calls this so
    ops like ring_attention agree with DistOpt's data_axis)."""
    global _data_axis
    _data_axis = name


def current_data_axis() -> str:
    return _data_axis


def make_mesh(axes: Dict[str, int], devices: Optional[Sequence] = None) -> Mesh:
    """Build a named mesh, e.g. make_mesh({'data': 4, 'model': 2})."""
    devices = list(devices) if devices is not None else jax.devices()
    n = int(np.prod(list(axes.values())))
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    arr = np.array(devices[:n]).reshape(tuple(axes.values()))
    return Mesh(arr, tuple(axes.keys()))


def data_parallel_mesh(n: Optional[int] = None) -> Mesh:
    devs = jax.devices()
    n = n or len(devs)
    return make_mesh({"data": n}, devs)


def set_mesh(mesh: Optional[Mesh]) -> None:
    global _current_mesh
    _current_mesh = mesh


def current_mesh() -> Optional[Mesh]:
    return _current_mesh


def mesh_shape() -> Dict[str, int]:
    m = current_mesh()
    return dict(m.shape) if m is not None else {}


def named_sharding(*spec) -> Optional[NamedSharding]:
    m = current_mesh()
    if m is None:
        return None
    return NamedSharding(m, P(*spec))


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def local_devices() -> List:
    return jax.local_devices()
