"""Shape-only memory/sharding planner (the TPU-native analog of the
reference Graph/Scheduler's memory planning — SURVEY.md §1.2 L2 "memory
planning"; exercised against the Llama-3-8B stretch config,
BASELINE.json:11).

Everything here is abstract: parameters are initialized under
``jax.eval_shape`` (no 16 GB of real weights), optimizer slots likewise,
and the FULL training step — forward, backward, collectives, update —
is ``jit.lower``-ed against a target mesh with the model's SHARD_RULES,
WITHOUT compiling or allocating.  The result reports exact per-device
parameter/optimizer/gradient bytes so "does this model fit a v4 chip's
HBM under this mesh?" is answerable before touching hardware.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import mesh as mesh_mod
from . import spmd

__all__ = ["abstract_init", "plan_train_step", "MemoryPlan", "HBM_BYTES"]

# per-chip HBM of the generations the metrics table knows about
HBM_BYTES = {
    "v2": 8 << 30, "v3": 16 << 30, "v4": 32 << 30,
    "v5e": 16 << 30, "v5p": 95 << 30, "v6e": 32 << 30,
}


def abstract_init(model, example_sds) -> None:
    """Materialize the model's parameter *shapes* without allocating:
    run the lazy-init forward under eval_shape, then rebind every param
    Tensor's data to a ShapeDtypeStruct."""
    from .. import autograd
    from .. import tensor as tensor_mod
    from ..model import model_device
    from ..tensor import Tensor

    dev = model_device(model)

    def fwd(*arrs):
        prev = autograd.is_training()
        autograd.set_training(False)
        try:
            ts = tuple(Tensor(data=a, device=dev, requires_grad=False)
                       for a in arrs)
            out = model.forward(*ts)
            leaf = out[0] if isinstance(out, (tuple, list)) else out
            return leaf.data
        finally:
            autograd.set_training(prev)

    saved_key = tensor_mod._rng_key    # init draws keys under the trace;
    try:                               # the global must not keep a tracer
        jax.eval_shape(fwd, *example_sds)
    finally:
        tensor_mod._rng_key = saved_key
    # params now hold leaked tracers; shape/dtype are safe to read —
    # swap them for honest abstract values
    for t in list(model.get_params().values()) + \
            list(model._get_buffers().values()):
        t.data = jax.ShapeDtypeStruct(tuple(t.data.shape), t.data.dtype)


def _reset_lazy(layer) -> None:
    """Recursively clear lazy-init state so the next forward re-creates
    concrete parameters (planner leaves abstract data behind)."""
    layer._initialized = False
    layer._params.clear()
    layer._states.clear()
    for sub in layer._sublayers.values():
        _reset_lazy(sub)


@dataclass
class MemoryPlan:
    """Per-device memory accounting for one compiled train step.

    Gradient accounting: `grad_bytes_per_device` is the BACKWARD PEAK —
    one full gradient set at the params' shardings (gradients
    materialize at param shardings before the update consumes them,
    ZeRO-1 or not).  Under ZeRO-1 (`DistOpt(shard_weight_update=True)`)
    the update itself only holds the reduce-scattered 1/W shard —
    reported separately as `grad_bytes_update_per_device` — and the
    durable saving shows up in `slot_bytes_per_device`, whose moments
    are sharded over the data axis.  A GradAccum wrapper's f32
    accumulator is part of the optimizer state tree (opt.init), so it
    is counted in `slot_bytes_per_device`, not here.
    """

    mesh_shape: Dict[str, int]
    param_bytes_global: int
    param_bytes_per_device: int
    slot_bytes_per_device: int
    grad_bytes_per_device: int
    # gradient residency during the (possibly ZeRO-1-sharded) update
    grad_bytes_update_per_device: int = 0
    per_device_state_bytes: int = field(init=False)
    lowered: object = None

    def __post_init__(self):
        if not self.grad_bytes_update_per_device:
            self.grad_bytes_update_per_device = self.grad_bytes_per_device
        self.per_device_state_bytes = (self.param_bytes_per_device
                                       + self.slot_bytes_per_device
                                       + self.grad_bytes_per_device)

    def fits(self, chip: str = "v4", headroom: float = 0.75) -> bool:
        """True when params + moments + one peak gradient set leave
        `1-headroom` of the chip's HBM for activations/workspace."""
        return self.per_device_state_bytes <= HBM_BYTES[chip] * headroom


def _sharded_bytes(shape, dtype, sharding) -> int:
    """Exact per-device bytes of an array under a NamedSharding."""
    spec = sharding.spec
    mesh = sharding.mesh
    elems = int(np.prod(shape)) if shape else 1
    denom = 1
    for ax in spec:
        if ax is None:
            continue
        for a in (ax if isinstance(ax, tuple) else (ax,)):
            denom *= mesh.shape[a]
    return math.ceil(elems / denom) * np.dtype(dtype).itemsize


def plan_train_step(model, optimizer, batch_sds,
                    mesh: Optional[mesh_mod.Mesh] = None,
                    lower: bool = True) -> MemoryPlan:
    """Abstract-init `model`, derive SHARD_RULES shardings over `mesh`,
    optionally jit.lower the full train step (no compile), and return
    the per-device memory accounting.

    `batch_sds`: tuple of jax.ShapeDtypeStruct for train_one_batch args."""
    from ..model import _StepExecutor
    from ..opt import DistOpt

    mesh = mesh or mesh_mod.current_mesh()
    if mesh is None:
        raise ValueError("plan_train_step needs a mesh")
    abstract_init(model, batch_sds[:1])

    params = {n: t.data for n, t in model.get_params().items()}
    rules = spmd.collect_shard_rules(model)
    shardings = spmd.param_shardings(params, rules, mesh)
    # init under the TARGET mesh: slot shapes may depend on it (the
    # int8_ring error-feedback residual carries a (world, ...) rank axis)
    _saved_mesh = mesh_mod.current_mesh()
    mesh_mod.set_mesh(mesh)
    try:
        slots_abs = jax.eval_shape(optimizer.init, params)
    finally:
        mesh_mod.set_mesh(_saved_mesh)
    slot_sh = spmd.tree_shardings(slots_abs, shardings, mesh,
                                  {n: p.shape for n, p in params.items()},
                                  zero1_axis=spmd.zero1_axis_for(optimizer,
                                                                 mesh))

    pb_global = sum(int(np.prod(p.shape)) * np.dtype(p.dtype).itemsize
                    for p in params.values())
    pb_dev = sum(_sharded_bytes(p.shape, p.dtype, shardings[n])
                 for n, p in params.items())
    sb_dev = 0
    for n, sub in slots_abs.items():
        for leaf, sh in zip(jax.tree.leaves(sub),
                            jax.tree.leaves(slot_sh[n],
                                            is_leaf=lambda x: hasattr(x, "spec"))):
            sb_dev += _sharded_bytes(leaf.shape, leaf.dtype, sh)
    # backward peak: one gradient set at param shardings; update-time
    # residency shrinks 1/W under ZeRO-1 (reduce-scattered into the
    # sharded update) — see MemoryPlan docstring
    gb_dev = pb_dev
    zero1_ax = spmd.zero1_axis_for(optimizer, mesh)
    gb_upd = (math.ceil(pb_dev / mesh.shape[zero1_ax])
              if zero1_ax else pb_dev)

    lowered = None
    if lower:
        saved_opt = model.optimizer
        model.set_optimizer(optimizer)
        saved = mesh_mod.current_mesh()
        mesh_mod.set_mesh(mesh)
        try:
            ex = _StepExecutor.for_planning(model, optimizer, slots_abs,
                                            batch_sds)
            step_sds = jax.ShapeDtypeStruct((), jnp.int32)
            rng_sds = jax.ShapeDtypeStruct((2,), jnp.uint32)
            buffers = {n: t.data for n, t in ex.buffer_tensors.items()}
            lowered = ex._jitted.lower(params, buffers, slots_abs,
                                       step_sds, rng_sds, *batch_sds)
        finally:
            mesh_mod.set_mesh(saved)
            model.optimizer = saved_opt

    # planning consumed the lazy params (they are ShapeDtypeStructs now):
    # clear lazy-init state so the model re-initializes real weights on
    # its next compile/forward instead of crashing on abstract data
    _reset_lazy(model)

    return MemoryPlan(mesh_shape=dict(mesh.shape),
                      param_bytes_global=pb_global,
                      param_bytes_per_device=pb_dev,
                      slot_bytes_per_device=sb_dev,
                      grad_bytes_per_device=gb_dev,
                      grad_bytes_update_per_device=gb_upd,
                      lowered=lowered)
