"""GSPMD sharding-rule machinery — multi-axis parallelism (DP × TP × SP).

The reference's only strategy is allreduce data-parallelism
(BASELINE.json:5); scaling past one chip's HBM (the Llama stretch,
BASELINE.json:11) is done the TPU way instead of new runtime machinery:
params get PartitionSpecs from per-model rules (regex over the param
path), the whole captured training step is jitted with those shardings,
and XLA/GSPMD inserts the collectives over ICI.

Rules format (see models.transformer.TRANSFORMER_SHARD_RULES):
    [(regex, spec_tuple), ...]   e.g. (r"q_proj\\.W$", (None, "model"))
First matching rule wins; axes that the installed mesh lacks, or that
don't divide the corresponding dim, are dropped (replicated) — so one
rule set serves 1-D DP, 2-D DP×TP, and 3-D DP×TP×SP meshes unchanged.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Tuple

import jax

from .mesh import Mesh, NamedSharding, P

__all__ = ["spec_for", "param_shardings", "batch_spec", "tree_shardings",
           "set_trace_rules", "current_trace_rules",
           "collect_shard_rules", "zero1_axis_for"]


def zero1_axis_for(optimizer, mesh: Optional[Mesh]) -> Optional[str]:
    """The data axis to shard optimizer moments over (ZeRO-1), or None.

    Single source of truth for eligibility (used by the graph executor
    and the planner): DistOpt-style optimizer with shard_weight_update,
    a mesh whose data axis has >1 devices, and no compressed/sparsified
    allreduce (those run on the shard_map path, which ZeRO-1 does not)."""
    if not getattr(optimizer, "shard_weight_update", False):
        return None
    axis = getattr(optimizer, "data_axis", None)
    if axis is None or mesh is None or mesh.shape.get(axis, 0) <= 1:
        return None
    if getattr(optimizer, "compress_dtype", None) is not None \
            or getattr(optimizer, "topk_ratio", 0.0) \
            or getattr(optimizer, "compression", None) is not None:
        import warnings
        warnings.warn(
            "shard_weight_update is ignored when compressed/sparsified "
            "allreduce is configured: those variants run on the "
            "shard_map data-parallel path, which does not shard the "
            "weight update (the error-feedback residual slots remain "
            "ZeRO-shardable state — tree_shardings partitions them "
            "whenever the GSPMD path is taken)", stacklevel=3)
        return None
    return axis


# trace-scoped SHARD_RULES: the graph executor installs the model's
# merged rules while tracing its step so axis-aware ops deep inside the
# trace (layer.PipelineStack's stacked block weights) can derive the
# same per-param specs the executor pinned on the unstacked params —
# without a structural path from layer to model.
_trace_rules: Optional[list] = None


def set_trace_rules(rules) -> None:
    global _trace_rules
    _trace_rules = rules


def current_trace_rules() -> Optional[list]:
    return _trace_rules


def collect_shard_rules(model) -> list:
    """Model-level SHARD_RULES followed by any sublayer-declared rules
    (e.g. layer.MoE's expert sharding) — first match wins, so model
    rules override layer defaults."""
    rules = list(getattr(model, "SHARD_RULES", None) or [])
    seen = {id(r) for r in rules}

    def walk(l):
        lr = getattr(type(l), "SHARD_RULES", None)
        if lr and l is not model:
            for r in lr:
                if id(r) not in seen:
                    rules.append(r)
                    seen.add(id(r))
        for sub in getattr(l, "_sublayers", {}).values():
            walk(sub)

    walk(model)
    return rules or None


def spec_for(name: str, shape: Sequence[int], rules, mesh: Mesh) -> P:
    """PartitionSpec for a param path under `rules`, pruned to `mesh`."""
    if not rules:
        return P()
    for pat, spec in rules:
        if re.search(pat, name):
            axes = []
            for i, ax in enumerate(spec):
                if (ax is not None and ax in mesh.shape and i < len(shape)
                        and shape[i] % mesh.shape[ax] == 0
                        and shape[i] >= mesh.shape[ax]):
                    axes.append(ax)
                else:
                    axes.append(None)
            while axes and axes[-1] is None:
                axes.pop()
            return P(*axes)
    return P()


def param_shardings(params: Dict[str, "jax.Array"], rules,
                    mesh: Mesh) -> Dict[str, NamedSharding]:
    return {n: NamedSharding(mesh, spec_for(n, p.shape, rules, mesh))
            for n, p in params.items()}


def batch_spec(shape: Sequence[int], dtype, mesh: Mesh,
               data_axis: str = "data", seq_axis: str = "seq") -> P:
    """Input-batch spec: dim 0 over the data axis; for token-id arrays
    (2-D integer), dim 1 additionally over the seq axis — GSPMD-style
    sequence parallelism for long context."""
    axes: List[Optional[str]] = []
    if (shape and data_axis in mesh.shape
            and shape[0] % mesh.shape[data_axis] == 0):
        axes.append(data_axis)
    else:
        axes.append(None)
    import numpy as np
    if (len(shape) == 2 and np.issubdtype(np.dtype(dtype), np.integer)
            and seq_axis in mesh.shape
            and shape[1] % mesh.shape[seq_axis] == 0):
        axes.append(seq_axis)
    while axes and axes[-1] is None:
        axes.pop()
    return P(*axes)


def tree_shardings(tree, name_to_sharding: Dict[str, NamedSharding],
                   mesh: Mesh, param_shapes: Optional[Dict[str, Tuple]] = None,
                   zero1_axis: Optional[str] = None):
    """Map a {name: slot-pytree} dict (optimizer state) to shardings:
    every leaf under `name` shares the param's sharding when shapes
    match, else is replicated.

    `zero1_axis`: cross-replica weight-update sharding (ZeRO-1; the
    "Automatic Cross-Replica Sharding of Weight Update" approach from
    PAPERS.md, expressed GSPMD-style): slot leaves that would otherwise
    be fully replicated are sharded over this (data) axis on dim 0 when
    divisible, so optimizer moments cost 1/N HBM per device and XLA
    partitions the update math to match (reduce-scatter the grads,
    update the owned shard, all-gather the params)."""
    rep = NamedSharding(mesh, P())
    nshard = mesh.shape.get(zero1_axis, 0) if zero1_axis else 0
    out = {}
    for name, sub in tree.items():
        sh = name_to_sharding.get(name, rep)
        pshape = param_shapes.get(name) if param_shapes else None

        def pick(leaf, sh=sh, pshape=pshape):
            if pshape is not None and tuple(getattr(leaf, "shape", ())) != tuple(pshape):
                return rep
            shape = tuple(getattr(leaf, "shape", ()))
            if (nshard > 1 and all(ax is None for ax in sh.spec)
                    and shape and shape[0] % nshard == 0
                    and shape[0] >= nshard):
                return NamedSharding(mesh, P(zero1_axis))
            return sh

        out[name] = jax.tree.map(pick, sub)
    return out
