"""Device layer: CppCPU / TpuDevice over PJRT (via JAX), mirroring the
reference's ``singa::Device`` hierarchy (capability contract:
/root/repo/BASELINE.json:5 — "add a `singa::TpuDevice` alongside
CppCPU/CudaGPU so Tensor math dispatches to XLA").

TPU-first design notes
----------------------
The reference lineage's Device owns raw memory and an execution stream and
receives ops as closures.  On TPU the idiomatic equivalent is a PJRT
client: memory is device buffers managed by the runtime, and "streams" are
the XLA executable launch queue.  We expose the same *API shape*
(``create_device``, device-owned allocation, host<->device copy) but let
PJRT/XLA own scheduling.  The CppCPU device doubles as the debug/smoke
device (BASELINE.json:7) and can dispatch hot-path math to the native C++
kernel library in ``csrc/`` (see singa_tpu/_core).
"""

from __future__ import annotations

import os
from typing import Any, List, Optional

import jax
import numpy as np

__all__ = [
    "Device",
    "CppCPU",
    "TpuDevice",
    "Platform",
    "create_device",
    "create_cpu_device",
    "create_tpu_device",
    "get_default_device",
    "set_default_device",
    "enable_lazy_alloc",
    "pjrt_plugin_info",
    "pjrt_native_probe",
]

# dtype aliases used across the framework (proto-enum parity kept in
# singa_tpu/proto). We use numpy dtypes as the neutral currency.
float16 = np.float16
bfloat16 = jax.numpy.bfloat16
float32 = np.float32
int32 = np.int32
int64 = np.int64
uint8 = np.uint8


class Device:
    """Base device.

    A Device owns:
      * a list of underlying ``jax.Device`` objects (1 for a single chip,
        many when the device represents a mesh slice),
      * a default floating dtype (bf16 on TPU, f32 on CPU),
      * an execution backend tag: ``"xla"`` (jnp/XLA compute) or
        ``"cpp"`` (native eager kernels from csrc/ for debug paths).
    """

    def __init__(self, name: str, jax_devices: List[Any], backend: str = "xla",
                 default_dtype=np.float32):
        self.name = name
        self.jax_devices = list(jax_devices)
        self.backend = backend
        self.default_dtype = default_dtype
        self.id = jax_devices[0].id if jax_devices else -1
        # graph/buffering flag: models flip this via Model.compile()
        self.graph_enabled = False
        self._verbosity = 0

    # -- reference-API compatibility surface ---------------------------------
    def SetRandSeed(self, seed: int) -> None:  # noqa: N802 (reference casing)
        from . import tensor as _t
        _t.set_seed(seed)

    def EnableGraph(self, enabled: bool) -> None:  # noqa: N802
        self.graph_enabled = bool(enabled)

    def SetVerbosity(self, v: int) -> None:  # noqa: N802
        self._verbosity = int(v)

    def ResetGraph(self) -> None:  # noqa: N802
        from .graph import reset_graph
        reset_graph(self)

    def Sync(self) -> None:  # noqa: N802
        """Block until all queued work on this device is complete."""
        # XLA dispatch is async; a block_until_ready on a trivial op on the
        # device flushes the queue.
        jax.block_until_ready(jax.device_put(0.0, self.jax_devices[0]))

    # -- memory ---------------------------------------------------------------
    def put(self, array) -> Any:
        """Place a host array onto this device (single-chip placement)."""
        return jax.device_put(array, self.jax_devices[0])

    def fetch(self, array) -> np.ndarray:
        """Device -> host copy."""
        return np.asarray(array)

    @property
    def is_tpu(self) -> bool:
        return self.jax_devices[0].platform in ("tpu", "axon")

    def memory_stats(self) -> dict:
        d = self.jax_devices[0]
        try:
            return dict(d.memory_stats() or {})
        except Exception:
            return {}

    def __repr__(self) -> str:
        return f"<{type(self).__name__} name={self.name} ndev={len(self.jax_devices)} backend={self.backend}>"


class CppCPU(Device):
    """Host CPU device — the debug/smoke device (BASELINE.json:7).

    Math runs eagerly; the hot kernels dispatch to the native C++
    library (csrc/tensor_math_cpp.cc) BY DEFAULT — mirroring the
    reference's tensor_math_cpp dispatch table — and degrade to XLA:CPU
    when the library is unavailable or shapes/dtypes don't qualify, so
    op coverage is total either way.  use_native=False forces pure XLA.
    """

    def __init__(self, use_native: bool = True):
        # process-LOCAL devices: under multi-host (init_distributed),
        # jax.devices() is the global list and other hosts' devices are
        # not addressable for eager placement
        cpus = [d for d in jax.local_devices() if d.platform == "cpu"]
        if not cpus and _has_platform("cpu"):
            cpus = [d for d in jax.devices("cpu")
                    if d.process_index == jax.process_index()]
        if not cpus:
            cpus = [jax.local_devices()[0]]
        super().__init__("CppCPU", cpus[:1], backend="cpp" if use_native else "xla",
                         default_dtype=np.float32)
        self.use_native = use_native


class TpuDevice(Device):
    """TPU device over PJRT (libtpu), the north-star addition
    (BASELINE.json:5). ``id`` selects a local chip; math dispatches to XLA
    and runs bf16 by default to keep the MXU fed."""

    def __init__(self, id: int = 0, default_dtype=None):
        tpus = _accelerator_devices()
        if not tpus:
            raise RuntimeError(
                "No TPU/accelerator platform visible to PJRT. "
                "Use create_cpu_device() or set JAX_PLATFORMS.")
        dev = tpus[min(id, len(tpus) - 1)]
        super().__init__(f"TPU:{dev.id}", [dev], backend="xla",
                         default_dtype=default_dtype or jax.numpy.bfloat16)


def _has_platform(name: str) -> bool:
    try:
        return len(jax.devices(name)) > 0
    except RuntimeError:
        return False


def _accelerator_devices():
    # process-local: a host may only place eager buffers on its own chips
    return [d for d in jax.local_devices() if d.platform not in ("cpu",)]


class Platform:
    """Static queries over available hardware (reference: singa::Platform)."""

    @staticmethod
    def GetNumGPUs() -> int:  # noqa: N802 — reference casing; counts accelerators
        return len(_accelerator_devices())

    @staticmethod
    def GetNumTPUs() -> int:  # noqa: N802
        return len(_accelerator_devices())

    @staticmethod
    def CreateTpuDevices(num: int) -> List["TpuDevice"]:  # noqa: N802
        return [TpuDevice(i) for i in range(num)]

    @staticmethod
    def DeviceQuery() -> str:  # noqa: N802
        lines = []
        for d in jax.devices():
            lines.append(f"{d.id}: platform={d.platform} kind={getattr(d, 'device_kind', '?')}")
        return "\n".join(lines)


_default_device: Optional[Device] = None


def create_cpu_device(use_native: bool = True) -> CppCPU:
    return CppCPU(use_native=use_native)


def create_tpu_device(id: int = 0) -> TpuDevice:
    return TpuDevice(id)


def create_device(kind: str = "auto", id: int = 0) -> Device:
    """The one line that changes when moving CPU -> TPU (BASELINE.json:5).

    kind: 'auto' | 'cpu' | 'cppcpu' | 'tpu' | 'gpu' ('gpu' maps to the
    accelerator for scripts written against the CUDA lineage).
    """
    kind = kind.lower()
    if kind == "auto":
        kind = "tpu" if _accelerator_devices() else "cpu"
    if kind in ("cpu", "cppcpu", "host"):
        return create_cpu_device()
    if kind in ("tpu", "gpu", "cuda", "accelerator"):
        return create_tpu_device(id)
    raise ValueError(f"unknown device kind: {kind!r}")


def get_default_device() -> Device:
    global _default_device
    if _default_device is None:
        _default_device = create_device("auto")
    return _default_device


def set_default_device(dev: Device) -> None:
    global _default_device
    _default_device = dev


def enable_lazy_alloc(flag: bool) -> None:
    """Reference-API no-op: PJRT owns allocation; kept for compatibility."""
    del flag


# ---------------------------------------------------------------------------
# native PJRT touchpoint (csrc/pjrt_device.cc) — SURVEY §7.1
# ---------------------------------------------------------------------------

def _default_plugin_path() -> Optional[str]:
    import importlib.util
    spec = importlib.util.find_spec("libtpu")
    if spec and spec.submodule_search_locations:
        p = os.path.join(list(spec.submodule_search_locations)[0],
                         "libtpu.so")
        if os.path.exists(p):
            return p
    return None


def pjrt_plugin_info(path: Optional[str] = None,
                     init: bool = True) -> dict:
    """Load a PJRT plugin through the NATIVE C++ core and return the
    C-API handshake: {path, api_struct_size, api_version: (major,
    minor), attributes: {name: value}, init_error}.

    This is the device layer's C++ entry onto the TPU runtime
    (csrc/pjrt_device.cc over the official pjrt_c_api.h).  It does NOT
    create a client — safe even when the tunneled backend is wedged.
    Raises RuntimeError if the native core or the plugin is
    unavailable."""
    import ctypes as C

    from . import _core

    l = _core.lib()
    if l is None:
        raise RuntimeError("native core unavailable (csrc build failed)")
    path = path or _default_plugin_path()
    if not path:
        raise RuntimeError("no PJRT plugin path given and libtpu not found")
    err = C.create_string_buffer(512)
    h = l.sg_pjrt_load(path.encode(), 1 if init else 0, err, 512)
    if h < 0:
        raise RuntimeError(f"PJRT plugin load failed: {err.value.decode()}")
    major, minor = C.c_int32(), C.c_int32()
    ssize = l.sg_pjrt_api_version(h, C.byref(major), C.byref(minor))
    attrs = {}
    n = l.sg_pjrt_attr_count(h)
    nb, vb = C.create_string_buffer(256), C.create_string_buffer(4096)
    for i in range(max(0, n)):
        if l.sg_pjrt_attr_get(h, i, nb, 256, vb, 4096) >= 0:
            attrs[nb.value.decode()] = vb.value.decode()
    l.sg_pjrt_init_error(h, vb, 4096)
    return {"path": path, "api_struct_size": int(ssize),
            "api_version": (major.value, minor.value),
            "attributes": attrs, "init_error": vb.value.decode(),
            "_handle": int(h)}


def pjrt_native_probe(path: Optional[str] = None) -> dict:
    """OPT-IN deep probe: create a PJRT client through the native core
    and enumerate devices (platform name, per-device description).

    WARNING: client creation over a wedged tunneled backend can block
    indefinitely — call this in a subprocess with a timeout (the same
    discipline as bench.py's TPU probe), and never while another client
    in this process already holds the chip."""
    import ctypes as C

    from . import _core

    info = pjrt_plugin_info(path)
    l = _core.lib()
    err = C.create_string_buffer(1024)
    c = l.sg_pjrt_client_create(info["_handle"], err, 1024)
    if c < 0:
        raise RuntimeError(f"PJRT client create failed: {err.value.decode()}")
    try:
        buf = C.create_string_buffer(4096)
        l.sg_pjrt_client_platform(c, buf, 4096)
        platform = buf.value.decode()
        ndev = l.sg_pjrt_client_device_count(c)
        devices = []
        for i in range(max(0, ndev)):
            if l.sg_pjrt_device_desc(c, i, buf, 4096) == 0:
                devices.append(buf.value.decode())
        return {**{k: v for k, v in info.items() if k != "_handle"},
                "platform": platform, "num_devices": int(ndev),
                "devices": devices}
    finally:
        l.sg_pjrt_client_destroy(c)
