"""RunState — the schema-versioned resume bundle of a training run.

A checkpoint that holds only params+moments can restore the *model*,
but not the *run*: the data-iterator position, the RNG trajectory, and
the step/epoch counters are what make a resumed run reproduce the
uninterrupted one bit-for-bit.  RunState packages exactly that state as a
plain JSON-able dict carried in the checkpoint aux (under
:data:`AUX_RUN_STATE`), versioned so a future layout change fails
loudly instead of resuming from a misread bundle.

Conventions:

* ``step`` counts **completed** steps — a RunState with ``step=k``
  resumes execution at step index ``k`` (0-based).
* ``rng_key`` is the model's base PRNG key as a list of uint32 words;
  restoring it makes per-step ``fold_in`` keys (dropout etc.) replay
  the uninterrupted sequence.
* ``data_state`` is whatever the loader's ``state_dict()`` returned
  (see :meth:`singa_tpu.utils.data.DataLoader.state_dict`); it is
  applied back verbatim via ``load_state_dict``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

import numpy as np

from ..obs import schema

__all__ = ["RunState", "AUX_RUN_STATE", "RUN_STATE_VERSION"]

#: aux key the orchestrator stores the bundle under
AUX_RUN_STATE = "run_state"

#: bump when the bundle layout changes incompatibly
RUN_STATE_VERSION = 1


@dataclasses.dataclass
class RunState:
    step: int                               # steps completed so far
    epoch: int                              # data epochs completed
    data_state: Optional[Dict[str, Any]]    # DataLoader.state_dict()
    rng_key: Optional[List[int]]            # model._base_key words
    model_step_count: int                   # Model._step_count
    run_id: str
    version: int = RUN_STATE_VERSION

    # -- capture / restore -------------------------------------------------
    @classmethod
    def capture(cls, model, loader, step: int, run_id: str,
                data_state: Optional[Dict[str, Any]] = None) -> "RunState":
        """Snapshot the run-level state after ``step`` completed steps.

        ``data_state`` overrides the loader's live cursor (the
        emergency-checkpoint path passes the pre-draw cursor of a step
        that never completed)."""
        if data_state is None and loader is not None \
                and hasattr(loader, "state_dict"):
            data_state = dict(loader.state_dict())
        rng = None
        key = getattr(model, "_base_key", None)
        if key is not None:
            rng = [int(w) for w in np.asarray(key).ravel().tolist()]
        # .get: the loader contract is duck-typed (any state_dict()
        # counts), and capture runs inside the emergency-checkpoint
        # path where a KeyError would lose the save
        epoch = int(data_state.get("epoch", 0)) if data_state else 0
        return cls(step=int(step), epoch=epoch, data_state=data_state,
                   rng_key=rng,
                   model_step_count=int(getattr(model, "_step_count", 0)),
                   run_id=str(run_id))

    def apply(self, model, loader=None) -> None:
        """Restore the captured trajectory onto a fresh model/loader
        (params and optimizer moments are the checkpoint file's job —
        this handles everything around them)."""
        if self.rng_key is not None and hasattr(model, "_base_key"):
            import jax.numpy as jnp
            model._base_key = jnp.asarray(
                np.array(self.rng_key, dtype=np.uint32))
        if hasattr(model, "_step_count"):
            model._step_count = int(self.model_step_count)
        if (loader is not None and self.data_state is not None
                and hasattr(loader, "load_state_dict")):
            loader.load_state_dict(self.data_state)

    # -- (de)serialization -------------------------------------------------
    def to_aux(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_aux(cls, aux: Any, ctx: str = "run_state") -> "RunState":
        ver = schema.require(aux, "version", ctx)
        if ver != RUN_STATE_VERSION:
            raise schema.SchemaError(
                f"{ctx}: version {ver!r} is not the supported "
                f"{RUN_STATE_VERSION} — refusing to resume from a bundle "
                f"this code cannot interpret", field="version")
        return cls(step=int(schema.require(aux, "step", ctx)),
                   epoch=int(schema.require(aux, "epoch", ctx)),
                   data_state=aux.get("data_state"),
                   rng_key=aux.get("rng_key"),
                   model_step_count=int(
                       schema.require(aux, "model_step_count", ctx)),
                   run_id=str(schema.require(aux, "run_id", ctx)),
                   version=int(ver))
