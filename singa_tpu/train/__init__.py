"""singa_tpu.train — fault-tolerant run orchestration (ISSUE 3).

The subsystem that owns a training run end to end and makes it survive
the failure modes a production fleet actually hits — preemption, torn
writes, wedged collectives, transient device errors:

* :mod:`~singa_tpu.train.loop` — :class:`TrainRunner`: steps the
  model, integrates Heartbeat + device liveness, retries transient
  failures with bounded backoff, converts repeated failure into a
  recorded clean abort after an emergency checkpoint.
* :mod:`~singa_tpu.train.ckpt` — :class:`AsyncCheckpointManager`:
  device→host snapshot on the step thread, serialization + atomic
  rename + commit marker on a background writer, keep-last-N /
  keep-every-M retention.  A torn write is never loadable.
* :mod:`~singa_tpu.train.state` — :class:`RunState`: schema-versioned
  bundle of step/epoch/data-cursor/RNG so a resumed run reproduces the
  uninterrupted trajectory bit-for-bit.
* :mod:`~singa_tpu.train.preempt` — :class:`PreemptionHandler`:
  SIGTERM/SIGINT request checkpoint-and-exit at the next step boundary.

See docs/training.md for the run lifecycle, the checkpoint commit
protocol, and resume semantics.
"""

from . import ckpt, loop, preempt, state
from .ckpt import AsyncCheckpointManager, CheckpointCorrupt
from .loop import TrainAborted, TrainResult, TrainRunner
from .preempt import PreemptionHandler
from .state import RunState

__all__ = ["ckpt", "loop", "preempt", "state", "AsyncCheckpointManager",
           "CheckpointCorrupt", "TrainRunner", "TrainResult",
           "TrainAborted", "PreemptionHandler", "RunState"]
