"""Preemption handling — SIGTERM/SIGINT become a checkpoint request.

Cluster schedulers preempt with SIGTERM and a grace window; a human
preempts with Ctrl-C.  Either way the right response is the same:
finish the step in flight, write a final checkpoint, exit cleanly so
the next incarnation resumes the trajectory.  :class:`PreemptionHandler`
turns the signal into a flag the :class:`~singa_tpu.train.loop.
TrainRunner` polls at each step boundary — signal-handler context does
no work itself (handlers run between bytecodes on the main thread; a
checkpoint write there could interleave with anything).

A second Ctrl-C (SIGINT) while the request is pending raises
KeyboardInterrupt — the operator asking twice means *now*, and losing
progress since the last periodic checkpoint is their call.
"""

from __future__ import annotations

import signal
import threading
import warnings
from typing import Optional, Tuple

__all__ = ["PreemptionHandler"]


class PreemptionHandler:
    """Installable SIGTERM/SIGINT → checkpoint-and-exit request flag.

        with PreemptionHandler() as p:
            for step in ...:
                train_step(...)
                if p.requested:
                    save_checkpoint(); break
    """

    def __init__(self, signals: Tuple[int, ...] = (signal.SIGTERM,
                                                   signal.SIGINT)):
        self.signals = tuple(signals)
        self._requested = threading.Event()
        self._prev: dict = {}
        self._installed = False
        self._signum: Optional[int] = None

    @property
    def requested(self) -> bool:
        return self._requested.is_set()

    @property
    def signum(self) -> Optional[int]:
        """The signal that made the request (None until one arrives)."""
        return self._signum

    def _handle(self, signum, frame) -> None:
        if self._requested.is_set() and signum == signal.SIGINT:
            raise KeyboardInterrupt   # second Ctrl-C: exit NOW
        self._signum = signum  # singalint: disable=SGL010 signal handlers run between bytecodes ON the main thread (no parallel writer), and taking a lock here could deadlock against the interrupted holder
        self._requested.set()

    def install(self) -> "PreemptionHandler":
        """Idempotent; degrades to a no-op (with a warning) off the main
        thread, where CPython forbids installing handlers."""
        if self._installed:
            return self
        try:
            for s in self.signals:
                self._prev[s] = signal.signal(s, self._handle)
            self._installed = True
        except ValueError:   # not the main thread
            for s, prev in self._prev.items():
                signal.signal(s, prev)
            self._prev.clear()
            warnings.warn(
                "PreemptionHandler: not on the main thread; signals will "
                "not request checkpoints", stacklevel=2)
        return self

    def uninstall(self) -> None:
        """Restore the handlers that were installed before us."""
        if not self._installed:
            return
        for s, prev in self._prev.items():
            try:
                signal.signal(s, prev)
            except ValueError:   # pragma: no cover - teardown off-main
                pass
        self._prev.clear()
        self._installed = False

    def __enter__(self) -> "PreemptionHandler":
        return self.install()

    def __exit__(self, *exc) -> bool:
        self.uninstall()
        return False
