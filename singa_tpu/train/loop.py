"""TrainRunner — the run orchestrator: owns a training run end to end.

``Model.train_step`` steps the model; everything around it that turns
"a script that trains" into "a run that survives" lives here:

* **resume** — restore the newest intact checkpoint (params, optimizer
  moments, RNG trajectory, data cursor) and continue the uninterrupted
  trajectory bit-for-bit;
* **liveness** — a :class:`~singa_tpu.utils.failure.Heartbeat` watches
  for wedged steps (hung collective, dead tunnel) and converts silence
  into a recorded abort instead of an indefinite hang;
* **retry** — transient device errors (RuntimeError/OSError from the
  step) are retried with bounded exponential backoff and an active
  :func:`~singa_tpu.utils.failure.device_liveness_check` probe between
  attempts; repeated failure takes a final emergency checkpoint, writes
  the run record, and invokes ``on_fatal`` (default
  :func:`~singa_tpu.utils.failure.clean_abort`);
* **preemption** — SIGTERM/SIGINT request checkpoint-and-exit at the
  next step boundary (:mod:`singa_tpu.train.preempt`);
* **observability** — ``train.*`` spans/counters/gauges through
  :mod:`singa_tpu.obs.events`, and a ``train_run`` record appended to
  the durable store on completion/preemption/abort (linted by
  ``tools/record_check.py``).

Retry scope: a retry re-dispatches the SAME step.  That is sound for
dispatch-level transient errors (tunnel hiccup before launch); a
mid-execution device loss invalidates donated buffers and is exactly
what checkpoint-restart recovery is for — the fatal path, not the
retry path.
"""

from __future__ import annotations

import dataclasses
import threading
import time
import warnings
from typing import Any, Callable, Iterable, Optional, Tuple

import numpy as np

from ..obs import attr as obs_attr
from ..obs import events
from ..obs import flight as obs_flight
from ..obs import record as obs_record
from ..obs import trace as obs_trace
from ..utils import failure
from .ckpt import AsyncCheckpointManager
from .preempt import PreemptionHandler
from .state import AUX_RUN_STATE, RunState

__all__ = ["TrainRunner", "TrainResult", "TrainAborted"]


class TrainAborted(RuntimeError):
    """Raised (after the emergency checkpoint and run record land) when
    repeated step failure exhausts the retry budget and ``on_fatal``
    declined to end the process."""


@dataclasses.dataclass
class TrainResult:
    outcome: str          # "completed" | "preempted"
    steps: int            # total completed steps (including pre-resume)
    start_step: int       # first step index this incarnation executed
    resumed_from: int     # checkpoint step resumed from, -1 when fresh
    wall_s: float
    ckpt_count: int       # commits performed by this incarnation
    run_id: str


class TrainRunner:
    """Fault-tolerant training orchestrator.

        runner = TrainRunner(model, loader, total_steps=1000,
                             ckpt=AsyncCheckpointManager("ckpts",
                                                         save_every=50),
                             step_timeout=300.0,
                             record_store="runs/records.jsonl")
        result = runner.run()

    The model must be compiled (``model.compile(...)``) with its
    optimizer set before ``run()``; restore happens inside ``run()`` and
    invalidates compiled executors as needed, so compile-then-restore is
    the expected order.

    Parameters beyond the obvious:

    * ``heartbeat`` — a pre-built Heartbeat, or None; ``step_timeout``
      (seconds per step) builds one wired to the runner's fatal path.
    * ``max_retries``/``backoff_base``/``backoff_max`` — transient-error
      retry budget and exponential backoff bounds (seconds).
    * ``record_store`` — path of the durable run-record JSONL (None
      disables record keeping, e.g. in unit tests of other behavior).
    * ``on_fatal(msg)`` — invoked after the emergency checkpoint +
      record on unrecoverable failure; defaults to
      ``failure.clean_abort`` (process exit 42 so a launcher restarts
      into resume).  A callback that RETURNS causes TrainAborted to be
      raised instead.
    * ``on_step(step, outs)`` — post-step hook (metrics, schedulers,
      tests).
    """

    def __init__(self, model, loader: Optional[Iterable], total_steps: int,
                 *, ckpt: Optional[AsyncCheckpointManager] = None,
                 heartbeat: Optional[failure.Heartbeat] = None,
                 step_timeout: Optional[float] = None,
                 max_retries: int = 2, backoff_base: float = 0.25,
                 backoff_max: float = 4.0, liveness_timeout: float = 5.0,
                 preemptible: bool = True,
                 record_store: Optional[str] = None,
                 run_id: Optional[str] = None,
                 on_fatal: Optional[Callable[[str], Any]] = None,
                 on_step: Optional[Callable[[int, Any], Any]] = None,
                 to_batch: Optional[Callable[[Any], Tuple]] = None,
                 _sleep: Callable[[float], None] = time.sleep):
        if total_steps < 1:
            raise ValueError(f"total_steps must be >= 1, got {total_steps}")
        self.model = model
        self.loader = loader
        self.total_steps = int(total_steps)
        self.ckpt = ckpt
        self.max_retries = int(max_retries)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self.liveness_timeout = float(liveness_timeout)
        self.preemptible = preemptible
        self.record_store = record_store
        self.run_id = run_id or obs_record.new_run_id("train")
        self.on_fatal = on_fatal
        self.on_step = on_step
        self.to_batch = to_batch
        self._sleep = _sleep
        # _append_record races the heartbeat monitor thread against the
        # step thread (a hang-abort and a fatal-abort can land together);
        # the lock makes write-exactly-once true, not just likely
        self._record_lock = threading.Lock()
        self._record_written = False
        # the incident flight ring (ISSUE 11): bounded in-memory record
        # of recent steps/retries, dumped on the fatal/hung paths when
        # record_store names a place for the evidence
        self.flight = obs_flight.register(obs_flight.FlightRecorder())
        self._resumed_from = -1
        self._prestep_data: Optional[dict] = None
        self._ckpt0 = ckpt.committed_count if ckpt is not None else 0
        self._t0 = 0.0
        self.heartbeat = heartbeat
        if self.heartbeat is None and step_timeout is not None:
            self.heartbeat = failure.Heartbeat(
                timeout=float(step_timeout),
                on_failure=self._heartbeat_failure)

    # -- lifecycle ---------------------------------------------------------
    def run(self) -> TrainResult:
        # the whole run executes under one trace (the run_id): every
        # span/counter it emits — resume, per-step spans with their
        # retry attempts, checkpoint snapshot/write (the background
        # writer inherits via trace.capture/attach in train.ckpt) —
        # carries it, so `obsq trace <run_id>` renders the run timeline
        with obs_trace.activate(self.run_id):
            return self._run_traced()

    def _run_traced(self) -> TrainResult:
        self._t0 = time.perf_counter()
        start_step = self._restore()
        self._resumed_from = start_step if start_step > 0 else -1
        outcome = "completed"
        completed = start_step
        preempt = PreemptionHandler() if self.preemptible else None
        hb = self.heartbeat
        try:
            if preempt is not None:
                preempt.install()
            if hb is not None:
                hb.start()
            batches = self._batches()
            for step in range(start_step, self.total_steps):
                if self.ckpt is not None and self.loader is not None \
                        and hasattr(self.loader, "state_dict"):
                    # drawing the batch advances the loader cursor past
                    # this (not yet completed) step — the emergency
                    # checkpoint must save the PRE-draw cursor so a
                    # resumed run replays the failed step's own batch
                    self._prestep_data = dict(self.loader.state_dict())
                batch = next(batches)
                outs = self._step_with_retries(step, batch)
                completed = step + 1
                if hb is not None:
                    hb.beat(step)
                events.counter("train.steps", 1)
                self._emit_loss(step, outs)
                if self.on_step is not None:
                    self.on_step(step, outs)
                if preempt is not None and preempt.requested:
                    outcome = "preempted"
                    if hb is not None:
                        # the blocking final write may legitimately
                        # outlast a step timeout — it must not be shot
                        # down by the watchdog it just outlived
                        hb.stop()
                    self._save_checked(completed, force=True, block=True)
                    break
                self._save_checked(completed)
            else:
                # run complete: make the final state durable even when
                # total_steps doesn't land on the save cadence.  Wait
                # first — the cadence save for this very step may still
                # be in flight, and re-snapshotting it would turn the
                # async final save into a duplicate blocking write.
                if self.ckpt is not None:
                    self._wait_checked(completed)
                    if (not self.ckpt.steps()
                            or self.ckpt.steps()[-1] != completed):
                        self._save_checked(completed, force=True)
            if self.ckpt is not None:
                self._wait_checked(completed)
        finally:
            if hb is not None:
                hb.stop()
            if preempt is not None:
                preempt.uninstall()
        wall = time.perf_counter() - self._t0
        self._append_record(outcome, completed, wall)
        return TrainResult(
            outcome=outcome, steps=completed, start_step=start_step,
            resumed_from=self._resumed_from, wall_s=wall,
            ckpt_count=(self.ckpt.committed_count - self._ckpt0
                        if self.ckpt is not None else 0),
            run_id=self.run_id)

    def __enter__(self) -> "TrainRunner":
        return self

    def __exit__(self, *exc) -> bool:
        if self.heartbeat is not None:
            self.heartbeat.stop()
        if self.ckpt is not None:
            self.ckpt.close()
        return False

    # -- resume ------------------------------------------------------------
    def _restore(self) -> int:
        if self.ckpt is None:
            return 0
        with events.span("train.resume"):
            aux = self.ckpt.restore_latest(self.model)
        if aux is None:
            return 0
        if AUX_RUN_STATE in aux:
            rs = RunState.from_aux(aux[AUX_RUN_STATE])
            rs.apply(self.model, self.loader)
            start = rs.step
        else:
            # only commit-marked checkpoints are visible here, and only
            # AsyncCheckpointManager writes markers — so aux["step"] is
            # its convention: steps COMPLETED, i.e. the next step index
            start = int(aux.get("step", 0))
            warnings.warn(
                "resumed from a checkpoint without run_state: data "
                "order and RNG trajectory restart rather than resume",
                stacklevel=2)
        events.gauge("train.resumed_from", start)
        return start

    # -- stepping ----------------------------------------------------------
    def _batches(self):
        if self.loader is None:
            raise ValueError("TrainRunner needs a loader to draw batches "
                             "from (got None)")
        empty_epochs = 0
        while True:
            got = False
            for b in self.loader:
                got = True
                yield self._to_tensors(b)
            # a resumed cursor sitting exactly at an epoch boundary
            # legitimately yields an empty first iteration — two empty
            # epochs in a row means the loader is actually empty
            empty_epochs = 0 if got else empty_epochs + 1
            if empty_epochs >= 2:
                raise RuntimeError("DataLoader yielded no batches for two "
                                   "consecutive epochs")

    def _to_tensors(self, batch) -> Tuple:
        if self.to_batch is not None:
            return tuple(self.to_batch(batch))
        from ..model import model_device
        from ..tensor import Tensor
        dev = model_device(self.model)
        if not isinstance(batch, (tuple, list)):
            batch = (batch,)
        return tuple(
            b if isinstance(b, Tensor) or b is None
            else Tensor(data=np.asarray(b), device=dev, requires_grad=False)
            for b in batch)

    def _step_with_retries(self, step: int, batch: Tuple):
        from .. import faults
        attempt = 0
        while True:
            try:
                # "train.step" injection site: the retried region — an
                # injected InjectedFault is a RuntimeError, so it takes
                # the same backoff/liveness/fatal path a real transient
                # dispatch failure would
                # note BEFORE the injection site: a faulted attempt
                # must still show up in the flight timeline
                self.flight.note("span", "train.step", step=step,
                                 attempt=attempt)
                faults.fire("train.step", step=step, attempt=attempt)
                with events.span("train.step", step=step, attempt=attempt):
                    return self.model.train_step(
                        *(b for b in batch if b is not None))
            except (RuntimeError, OSError) as e:
                # ValueError/TypeError are bugs and propagate; runtime/OS
                # errors are where transient device trouble surfaces
                if isinstance(e, (TrainAborted, failure.FailureDetected)):
                    raise
                alive = True
                if attempt < self.max_retries:
                    alive = failure.device_liveness_check(
                        timeout=self.liveness_timeout)
                if attempt >= self.max_retries or not alive:
                    self._fatal(step,
                                f"train step {step} failed after "
                                f"{attempt + 1} attempt(s)"
                                f"{' (device liveness probe failed)' if not alive else ''}: "
                                f"{type(e).__name__}: {e}",
                                data_state=self._prestep_data)
                    raise TrainAborted(
                        f"step {step} unrecoverable: {e}") from e
                delay = min(self.backoff_max,
                            self.backoff_base * (2 ** attempt))
                attempt += 1
                events.counter("train.retries", 1, step=step,
                               backoff_s=delay)
                self.flight.note("counter", "train.retries", step=step,
                                 backoff_s=delay,
                                 error=type(e).__name__)
                warnings.warn(
                    f"train step {step} attempt {attempt} failed "
                    f"({type(e).__name__}: {e}); retrying in {delay:.2f}s",
                    stacklevel=2)
                self._sleep(delay)

    def _emit_loss(self, step: int, outs) -> None:
        if not events.enabled():
            return
        try:
            loss = outs[1] if isinstance(outs, tuple) and len(outs) > 1 \
                else outs
            data = getattr(loss, "data", loss)
            val = float(np.asarray(data))  # singalint: disable=SGL008 loss-gauge fetch runs only when telemetry is enabled, and the fetch IS the measurement
            events.gauge("train.loss", val, step=step)
        except Exception:   # telemetry must never break the step loop
            pass

    # -- checkpoint / failure ----------------------------------------------
    def _save(self, completed: int, force: bool = False,
              block: bool = False, data_state: Optional[dict] = None) -> None:
        if self.ckpt is None:
            return
        if not force and completed % self.ckpt.save_every:
            return   # mirror the manager's gate BEFORE paying for the
                     # RunState capture (host fetch of the PRNG key)
        rs = RunState.capture(self.model, self.loader, completed,
                              self.run_id, data_state=data_state)
        self.ckpt.save(completed, self.model, run_state=rs, force=force,
                       block=block)

    def _save_checked(self, completed: int, **kw) -> None:
        """A periodic/final save whose failure (typically a background
        write surfacing in wait(), e.g. ENOSPC) takes the fatal path —
        record + on_fatal — instead of escaping run() unrecorded."""
        try:
            self._save(completed, **kw)
        except Exception as e:
            self._ckpt_fatal(completed, e)

    def _wait_checked(self, completed: int) -> None:
        try:
            self.ckpt.wait()
        except Exception as e:
            self._ckpt_fatal(completed, e)

    def _ckpt_fatal(self, completed: int, e: Exception) -> None:
        self._fatal(completed,
                    f"checkpoint write at step {completed} failed: "
                    f"{type(e).__name__}: {e}")
        raise TrainAborted(
            f"checkpoint write at step {completed} failed: {e}") from e

    def _fatal(self, step: int, msg: str,
               data_state: Optional[dict] = None) -> None:
        """Emergency checkpoint → run record → on_fatal.  Ordered so the
        durable evidence lands even when on_fatal hard-exits.

        ``data_state`` overrides the loader cursor saved with the
        emergency checkpoint — the retry-exhaustion path passes the
        pre-draw cursor because its failed step never completed; the
        checkpoint-failure path leaves it None (its step count DID
        complete, so the live cursor is the right one)."""
        if self.heartbeat is not None:
            # the emergency save below may legitimately outlast a step
            # timeout; the watchdog must not kill the save it triggered
            self.heartbeat.stop()
        events.counter("train.aborts", 1, step=step)
        self.flight.note("counter", "train.aborts", step=step, msg=msg)
        if self.ckpt is not None:
            try:
                self._save(step, force=True, block=True,
                           data_state=data_state)
            except Exception as e:
                warnings.warn(f"emergency checkpoint failed: "
                              f"{type(e).__name__}: {e}", stacklevel=2)
        self._append_record("aborted", step,
                            time.perf_counter() - self._t0,
                            dump=lambda: self._flight_dump("train.fatal",
                                                           msg))
        (self.on_fatal or failure.clean_abort)(msg)

    def _heartbeat_failure(self, age: float, last_step: int) -> None:
        """Monitor-thread path: the step thread is wedged, so no
        checkpoint (the gather would wedge too) — record, then abort.
        (Runs trace-less by design: threads never inherit the run's
        trace context implicitly, and the hang observation is
        run-scoped evidence the record itself carries.)"""
        msg = (f"no heartbeat for {age:.1f}s (last step {last_step}); "
               f"assuming hung collective or dead device")
        events.counter("train.aborts", 1, step=last_step)
        self.flight.note("counter", "train.aborts", step=last_step,
                         msg=msg)
        self._append_record("hung", max(0, last_step + 1),
                            time.perf_counter() - self._t0,
                            dump=lambda: self._flight_dump("train.hung",
                                                           msg))
        (self.on_fatal or failure.clean_abort)(msg)

    # -- durable run record + flight dumps ---------------------------------
    def _flight_dump(self, site: str, reason: str) -> Optional[str]:
        """Dump the flight ring next to the record store and return the
        ``flight_ref`` (or None without a store) — the shared
        :func:`obs.flight.dump_for_store` contract; this thin wrapper
        exists so literal sites at call sites stay SGL009-checkable."""
        return obs_flight.dump_for_store(self.flight, site,
                                         self.record_store, reason)

    def _append_record(self, outcome: str, steps: int, wall_s: float,
                       dump: Optional[Callable[[], Optional[str]]] = None
                       ) -> None:
        if not self.record_store:
            return
        with self._record_lock:
            if self._record_written:
                return
            self._record_written = True
        # the dump thunk runs only after winning the write-exactly-once
        # race: a losing fatal path (step-thread abort vs heartbeat
        # firing together) must not strand an orphan dump that no
        # record's flight_ref points at
        flight_ref = dump() if dump is not None else None
        try:
            import jax
            platform = jax.default_backend()
            dev = jax.devices()[0]
            device_kind = getattr(dev, "device_kind", "") or platform
            payload = {
                "steps": int(steps),
                "wall_s": round(wall_s, 3),
                "ckpt_count": int(self.ckpt.committed_count - self._ckpt0
                                  if self.ckpt is not None else 0),
                "resumed_from": int(self._resumed_from),
                "outcome": outcome,
                "total_steps": int(self.total_steps),
            }
            if flight_ref:
                payload["flight_ref"] = flight_ref
            # runtime attribution (ISSUE 16): when a ledger is live,
            # the run's dispatch count/seconds ride along as numeric
            # extras — the schema allows extras, and obsq diff can
            # then put step-time drift next to the outcome fields
            led = obs_attr.get()
            if led is not None:
                snap = led.snapshot()
                payload["attr_dispatches"] = int(
                    sum(r["count"] for r in snap.values()))
                payload["attr_attributed_s"] = round(
                    sum(r["total_s"] for r in snap.values()), 6)
            entry = obs_record.new_entry(
                "train_run", platform, platform != "tpu", device_kind,
                run_id=self.run_id, payload=payload)
            obs_record.RunRecord(self.record_store).append(entry)
        except Exception as e:
            # the record is evidence, not a dependency: a full disk must
            # not turn a completed run into a crashed one
            warnings.warn(f"could not append train_run record: "
                          f"{type(e).__name__}: {e}", stacklevel=2)
