"""AsyncCheckpointManager — crash-consistent checkpoints off the step
thread.

Write protocol (the commit-marker contract ``tools/ckpt_fsck.py``
audits):

1. **snapshot** (step thread): params/buffers/optimizer moments are
   fetched to host with ``jax.device_get`` — this is the only part the
   training step waits for, and it must run on the step thread (the
   gather of sharded arrays is a collective, and the fetch must not
   race the next step's donated buffers).
2. **serialize** (writer thread): the snapshot is written via
   :func:`singa_tpu.utils.checkpoint.save_arrays` — temp file, fsync,
   atomic rename — so a crash mid-write never leaves a partial
   ``ckpt_<step>.npz`` under the final name.
3. **commit** (writer thread): a sidecar ``ckpt_<step>.npz.commit``
   marker is written (same temp+fsync+rename dance) carrying the
   npz's sha256 and size.  *Only checkpoints with a valid marker are
   ever loadable*: a torn npz (crash between 2 and 3, bit rot, manual
   truncation) fails the sha check and restore falls back to the
   previous commit.
4. **retain** (writer thread): keep-last-N plus keep-every-M GC; the
   marker is deleted before the npz so GC interrupted mid-way
   degrades to an uncommitted (ignored) file, never a committed
   marker pointing at nothing.

Telemetry: the snapshot emits a ``train.ckpt.snapshot`` span on the
step thread and the writer emits ``train.ckpt.write`` — overlapping
``train.step``/``train.ckpt.write`` spans are the observable proof
that serialization never blocked training (asserted in
tests/test_train.py).

Multi-host runs write synchronously (the end-of-save barrier is a
collective that must not interleave with training collectives — same
rule as ``utils.checkpoint.CheckpointManager``).
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import warnings
from typing import Dict, List, Optional

from ..obs import events
from ..obs import trace as obs_trace
from ..utils import checkpoint
from .state import AUX_RUN_STATE, RunState

__all__ = ["AsyncCheckpointManager", "CheckpointCorrupt", "COMMIT_SUFFIX",
           "read_marker", "sha256_file"]

COMMIT_SUFFIX = ".commit"


class CheckpointCorrupt(RuntimeError):
    """The checkpoint file exists but is not loadable (no/invalid commit
    marker, sha mismatch, torn npz, manifest mismatch)."""


def sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                return h.hexdigest()
            h.update(b)


def read_marker(path: str) -> Dict:
    """Parse a commit marker; raises CheckpointCorrupt on garbage."""
    try:
        with open(path) as f:
            doc = json.loads(f.read())
    except (OSError, json.JSONDecodeError) as e:
        raise CheckpointCorrupt(f"{path}: unreadable commit marker ({e})")
    if not isinstance(doc, dict) or "sha256" not in doc or "size" not in doc:
        raise CheckpointCorrupt(f"{path}: commit marker missing sha256/size")
    return doc


class AsyncCheckpointManager:
    """Stepped, crash-consistent checkpoints with a background writer.

        ckpt = AsyncCheckpointManager("ckpts", keep_last=3, keep_every=50,
                                      save_every=10)
        aux = ckpt.restore_latest(model)            # None when fresh
        ...
        ckpt.save(completed_steps, model, run_state=rs)
        ...
        ckpt.close()                                # final write lands

    ``save_every`` gates periodic saves (``force=True`` bypasses);
    ``keep_last`` newest commits are retained plus every commit whose
    step is a multiple of ``keep_every`` (0 disables the keep-every
    rule)."""

    def __init__(self, directory: str, keep_last: int = 3,
                 keep_every: int = 0, save_every: int = 1):
        if keep_last < 1:
            raise ValueError(f"keep_last must be >= 1, got {keep_last}")
        if keep_every < 0:
            raise ValueError(f"keep_every must be >= 0, got {keep_every}")
        self.dir = directory
        self.keep_last = keep_last
        self.keep_every = keep_every
        self.save_every = max(1, save_every)
        self.committed_count = 0   # commits performed by THIS manager
        self._pending = None
        self._executor = None
        os.makedirs(directory, exist_ok=True)

    # -- layout ------------------------------------------------------------
    def path(self, step: int) -> str:
        return os.path.join(self.dir, f"ckpt_{step:012d}.npz")

    def marker_path(self, step: int) -> str:
        return self.path(step) + COMMIT_SUFFIX

    def steps(self) -> List[int]:
        """Committed steps only (a marker must exist; its validity is
        checked at load time), ascending."""
        out = []
        for f in os.listdir(self.dir):
            if f.startswith("ckpt_") and f.endswith(".npz" + COMMIT_SUFFIX):
                try:
                    out.append(int(f[5:-len(".npz" + COMMIT_SUFFIX)]))
                except ValueError:
                    pass
        return sorted(out)

    # -- saving ------------------------------------------------------------
    def save(self, step: int, model, run_state: Optional[RunState] = None,
             aux: Optional[Dict] = None, force: bool = False,
             block: bool = False) -> Optional[str]:
        """Snapshot now (step thread), write in the background.

        ``step`` is the number of COMPLETED steps the snapshot
        represents (the RunState convention).  Returns the target path,
        or None when gated by ``save_every``.  At most one write is in
        flight: a new save first waits for the previous one (bounding
        host memory to one snapshot), which only blocks when the save
        cadence outruns the disk."""
        if not force and step % self.save_every:
            return None
        self.wait()                    # one in-flight snapshot at a time
        a = dict(aux or {})
        a["step"] = int(step)
        if run_state is not None:
            a[AUX_RUN_STATE] = run_state.to_aux()
        with events.span("train.ckpt.snapshot", step=step):
            arrays, full_aux = checkpoint._collect(model, a)
        if block or checkpoint._process_count() > 1:
            self._write(step, arrays, full_aux)
        else:
            if self._executor is None:
                from concurrent.futures import ThreadPoolExecutor
                # non-daemon single worker: joined at interpreter exit,
                # so the final write always lands (file IO cannot wedge
                # the way a dead device can — cf. Heartbeat, which IS
                # a daemon for exactly the opposite reason)
                self._executor = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="singa-train-ckpt")
            # the writer INHERITS the saving step's trace context
            # (threads never inherit contextvars implicitly): the
            # train.ckpt.write span belongs to the run/step whose
            # snapshot it serializes, so the overlap is visible inside
            # ONE trace instead of as an orphan span
            ctx = obs_trace.capture()
            self._pending = self._executor.submit(
                self._write_traced, ctx, step, arrays, full_aux)
        return self.path(step)

    def _write_traced(self, ctx, step: int, arrays: Dict,
                      aux: Dict) -> None:
        with obs_trace.attach(ctx):
            self._write(step, arrays, aux)

    def _write(self, step: int, arrays: Dict, aux: Dict) -> None:
        from .. import faults
        with events.span("train.ckpt.write", step=step):
            if checkpoint._process_index() == 0:
                # "ckpt.write" fires before any bytes land, so an
                # injected error surfaces through wait() exactly like
                # ENOSPC would — the caller's _save_checked fatal path
                faults.fire("ckpt.write", step=step, path=self.path(step))
                checkpoint.save_arrays(arrays, self.path(step), aux)
                self._commit(step)
                # "ckpt.torn" tears the npz AFTER its commit marker
                # landed: the sha-checked restore path must skip it
                faults.fire("ckpt.torn", step=step, path=self.path(step))
                self._gc()
            checkpoint._barrier(f"singa_train_ckpt_{step}")
        events.counter("train.ckpt.committed", 1, step=step)

    def _commit(self, step: int) -> None:
        path = self.path(step)
        doc = {"step": int(step), "sha256": sha256_file(path),
               "size": os.path.getsize(path)}
        checkpoint.atomic_write(self.marker_path(step),
                                lambda f: json.dump(doc, f), mode="w")
        self.committed_count += 1  # singalint: disable=SGL010 sole writer is the 1-worker ckpt executor; readers (ckpt_count in the run record) tolerate a stale count

    def _gc(self) -> None:
        steps = self.steps()
        protected = set(steps[-self.keep_last:])
        if self.keep_every:
            protected |= {s for s in steps if s % self.keep_every == 0}
        for s in steps:
            if s in protected:
                continue
            # marker first: an interruption here leaves an uncommitted
            # npz (ignored at load), never a dangling commit
            with contextlib.suppress(OSError):
                os.unlink(self.marker_path(s))
            with contextlib.suppress(OSError):
                os.unlink(self.path(s))
        events.gauge("train.ckpt.retained", len(self.steps()))

    def wait(self) -> None:
        """Block until the in-flight write lands; re-raises a background
        write failure."""
        if self._pending is not None:
            pending, self._pending = self._pending, None
            pending.result()

    def close(self) -> None:
        """Flush the writer; safe to call repeatedly."""
        try:
            self.wait()
        finally:
            if self._executor is not None:
                self._executor.shutdown(wait=True)
                self._executor = None

    # -- loading -----------------------------------------------------------
    def verify(self, step: int) -> None:
        """Commit-marker + sha check; CheckpointCorrupt when torn."""
        path = self.path(step)
        marker = self.marker_path(step)
        if not os.path.exists(marker):
            raise CheckpointCorrupt(f"{path}: no commit marker — the write "
                                    f"never committed")
        doc = read_marker(marker)
        if not os.path.exists(path):
            raise CheckpointCorrupt(f"{path}: committed but missing")
        size = os.path.getsize(path)
        if size != int(doc["size"]):
            raise CheckpointCorrupt(
                f"{path}: size {size} != committed {doc['size']} (torn)")
        sha = sha256_file(path)
        if sha != doc["sha256"]:
            raise CheckpointCorrupt(
                f"{path}: sha256 mismatch vs commit marker (torn/corrupt)")

    def load_step(self, step: int, model) -> Dict:
        """Load one committed checkpoint into ``model``; returns its aux.

        Raises CheckpointCorrupt for torn/unreadable files; a checkpoint
        that reads fine but does not FIT the model (optimizer signature
        or shape mismatch) raises ValueError — silently skipping past it
        would restart training from an older trajectory."""
        self.verify(step)
        try:
            arrays, aux = checkpoint.load_arrays(self.path(step))
        except Exception as e:
            raise CheckpointCorrupt(
                f"{self.path(step)}: committed but undecodable ({e})") from e
        checkpoint._apply(model, arrays, aux)
        return aux

    def restore_latest(self, model) -> Optional[Dict]:
        """Restore the newest intact commit; returns its aux dict (with
        ``aux['step']`` = completed steps and ``aux['run_state']`` when
        the orchestrator saved one), or None when starting fresh.  Torn
        commits are warned about and skipped, falling back to the
        previous one."""
        try:
            self.wait()
        except Exception as e:
            warnings.warn(
                f"a background checkpoint write had failed "
                f"({type(e).__name__}: {e}); restoring from the commits "
                f"on disk", stacklevel=2)
        for step in reversed(self.steps()):
            try:
                aux = self.load_step(step, model)
            except CheckpointCorrupt as e:
                warnings.warn(f"skipping torn checkpoint at step {step}: "
                              f"{e}", stacklevel=2)
                continue
            events.counter("train.ckpt.restored", 1, step=step)
            return aux
        return None

    def __enter__(self) -> "AsyncCheckpointManager":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False
