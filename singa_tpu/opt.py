"""singa_tpu.opt — optimizers + DistOpt (capability parity:
``singa.opt`` SGD/momentum and the NCCL-backed DistOpt of
BASELINE.json:5, whose allreduce we replace with XLA collectives over
ICI emitted *inside* the compiled step module).

Design: every optimizer has a pure functional core
    init(params)                    -> state  (dict name -> arrays)
    apply(step, name, p, g, state)  -> (new_p, new_state_slot)
used by the graph executor so the whole update compiles into the single
step HLO module.  The eager SINGA surface (``opt.update(p, g)``,
``opt(loss)``) drives the same core immediately.

DistOpt: marks gradients for mean-allreduce over the 'data' mesh axis.
Under the compiled step the executor runs inside shard_map over the
global mesh, so ``jax.lax.pmean`` lowers to one fused XLA all-reduce over
ICI — the fused-bucket behavior of the reference comes for free because
XLA's allreduce combiner merges small reduces.  fp16/bf16-compressed
allreduce mirrors the reference's `backward_and_update_half`
(BASELINE.json:5 "fused/sparsified grads").
"""

from __future__ import annotations

import math
from typing import Callable, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import autograd
from .tensor import Tensor

__all__ = [
    "Optimizer", "SGD", "Adam", "AdamW", "RMSProp", "AdaGrad",
    "Adafactor", "DistOpt", "GradAccum", "Constant", "ExponentialDecay",
    "CosineDecay", "WarmupCosine", "MultiStepLR",
]


# ---------------------------------------------------------------------------
# learning-rate schedules (scalar step -> lr; jit-safe, pure jnp)
# ---------------------------------------------------------------------------

class Schedule:
    def __call__(self, step):
        raise NotImplementedError


class Constant(Schedule):
    def __init__(self, lr: float):
        self.lr = lr

    def __call__(self, step):
        return self.lr


class ExponentialDecay(Schedule):
    def __init__(self, lr: float, decay_steps: int, decay_rate: float,
                 staircase: bool = False):
        self.lr, self.decay_steps = lr, decay_steps
        self.decay_rate, self.staircase = decay_rate, staircase

    def __call__(self, step):
        p = step / self.decay_steps
        if self.staircase:
            p = jnp.floor(p)
        return self.lr * jnp.power(self.decay_rate, p)


class CosineDecay(Schedule):
    def __init__(self, lr: float, total_steps: int, alpha: float = 0.0):
        self.lr, self.total_steps, self.alpha = lr, total_steps, alpha

    def __call__(self, step):
        frac = jnp.clip(step / self.total_steps, 0.0, 1.0)
        cos = 0.5 * (1 + jnp.cos(jnp.pi * frac))
        return self.lr * ((1 - self.alpha) * cos + self.alpha)


class WarmupCosine(Schedule):
    def __init__(self, lr: float, warmup_steps: int, total_steps: int,
                 min_lr: float = 0.0):
        self.lr, self.warmup, self.total, self.min_lr = lr, warmup_steps, total_steps, min_lr

    def __call__(self, step):
        warm = self.lr * step / max(1, self.warmup)
        frac = jnp.clip((step - self.warmup) / max(1, self.total - self.warmup), 0.0, 1.0)
        cos = self.min_lr + (self.lr - self.min_lr) * 0.5 * (1 + jnp.cos(jnp.pi * frac))
        return jnp.where(step < self.warmup, warm, cos)


class MultiStepLR(Schedule):
    def __init__(self, lr: float, milestones: List[int], gamma: float = 0.1):
        self.lr, self.milestones, self.gamma = lr, sorted(milestones), gamma

    def __call__(self, step):
        n = sum(jnp.where(step >= m, 1, 0) for m in self.milestones)
        return self.lr * jnp.power(self.gamma, n)


def _as_schedule(lr) -> Schedule:
    if isinstance(lr, Schedule):
        return lr
    return Constant(float(lr))


# ---------------------------------------------------------------------------
# optimizers
# ---------------------------------------------------------------------------

class Optimizer:
    def __init__(self, lr):
        self.sched = _as_schedule(lr)
        self.step_counter = 0

    # -- functional core ------------------------------------------------------
    def init(self, params: Dict[str, jnp.ndarray]) -> Dict:
        return {}

    def apply(self, step, name: str, p, g, slot):
        raise NotImplementedError

    def apply_all(self, step, params: Dict[str, jnp.ndarray],
                  grads: Dict[str, jnp.ndarray], state: Dict):
        """Update every param; used by the graph executor inside jit."""
        new_p, new_s = {}, {}
        for name, p in params.items():
            g = grads.get(name)
            if g is None:
                new_p[name] = p
                new_s[name] = state.get(name)
                continue
            np_, ns_ = self.apply(step, name, p, g.astype(p.dtype),
                                  state.get(name))
            new_p[name] = np_
            new_s[name] = ns_
        return new_p, new_s

    # -- eager SINGA surface --------------------------------------------------
    def update(self, param: Tensor, grad: Tensor) -> None:
        name = param.name or str(id(param))
        if getattr(self, "_eager_state", None) is None:
            self._eager_state = {}
        slot = self._eager_state.get(name)
        if slot is None:
            slot = self._init_slot(param.data)
        new_p, new_slot = self.apply(self.step_counter, name, param.data,
                                     grad.data.astype(param.dtype), slot)
        param.data = new_p
        self._eager_state[name] = new_slot

    def _init_slot(self, p):
        return None

    def __call__(self, loss: Tensor) -> None:
        """backward + update (reference `opt(loss)` convenience)."""
        for p, g in autograd.backward(loss):
            self.update(p, g)
        self.step()

    def backward_and_update(self, loss: Tensor) -> None:
        """Reference surface: same as __call__ for non-distributed opts,
        so user code written against DistOpt runs unchanged."""
        self(loss)

    def step(self) -> None:
        self.step_counter += 1

    def get_states(self) -> Dict:
        return {"step": self.step_counter}

    def set_states(self, s: Dict) -> None:
        self.step_counter = int(s.get("step", 0))

    def state_signature(self) -> str:
        """Identifies the slot STRUCTURE this optimizer produces.
        Checkpoints carry it so a restore into a structurally-coincident
        but different optimizer (e.g. Adam's (m, v) reinterpreted as
        GradAccum's {acc, base}) is rejected instead of silently
        corrupting the update."""
        return type(self).__name__

    # -- moment persistence (checkpoint/resume correctness) -------------------
    # The graph executor mirrors its compiled-step slots into _eager_state
    # after every step, so _eager_state is the canonical host-visible store
    # in both eager and graph mode.
    def slot_arrays(self) -> Dict[str, List]:
        """Per-param optimizer moment leaves (momentum buf, Adam m/v, ...)
        as {name: [leaf, ...]}; empty lists for stateless slots."""
        out = {}
        for name, slot in (getattr(self, "_eager_state", None) or {}).items():
            leaves = [l for l in jax.tree.leaves(slot)]
            out[name] = leaves
        return out

    def load_slot_arrays(self, slots: Dict[str, List]) -> None:
        """Rebuild _eager_state from serialized leaves (inverse of
        slot_arrays). Slot structure is reconstructed generically: 0
        leaves -> None, 1 leaf -> the array, N leaves -> tuple."""
        est = {}
        for name, leaves in slots.items():
            arrs = [jnp.asarray(l) for l in leaves]
            if not arrs:
                est[name] = None
            elif len(arrs) == 1:
                est[name] = arrs[0]
            else:
                est[name] = tuple(arrs)
        self._eager_state = est


class SGD(Optimizer):
    """SGD with momentum / nesterov / L2 weight decay (reference parity)."""

    def __init__(self, lr=0.1, momentum=0.0, weight_decay=0.0,
                 nesterov=False, dampening=0.0):
        super().__init__(lr)
        self.momentum = momentum
        self.weight_decay = weight_decay
        self.nesterov = nesterov
        self.dampening = dampening

    def init(self, params):
        if self.momentum == 0.0:
            return {n: None for n in params}
        return {n: jnp.zeros_like(p) for n, p in params.items()}

    def _init_slot(self, p):
        return None if self.momentum == 0.0 else jnp.zeros_like(p)

    def state_signature(self) -> str:
        return f"SGD(momentum={bool(self.momentum)})"

    def apply(self, step, name, p, g, slot):
        lr = self.sched(step)
        if self.weight_decay:
            g = g + self.weight_decay * p
        if self.momentum:
            buf = self.momentum * slot + (1 - self.dampening) * g
            g_eff = g + self.momentum * buf if self.nesterov else buf
            return (p - lr * g_eff).astype(p.dtype), buf
        return (p - lr * g).astype(p.dtype), None


class Adam(Optimizer):
    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0):
        super().__init__(lr)
        self.b1, self.b2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.decoupled = False

    def init(self, params):
        return {n: (jnp.zeros_like(p), jnp.zeros_like(p))
                for n, p in params.items()}

    def _init_slot(self, p):
        return (jnp.zeros_like(p), jnp.zeros_like(p))

    def apply(self, step, name, p, g, slot):
        lr = self.sched(step)
        m, v = slot
        if self.weight_decay and not self.decoupled:
            g = g + self.weight_decay * p
        t = step + 1
        m = self.b1 * m + (1 - self.b1) * g
        v = self.b2 * v + (1 - self.b2) * (g * g)
        mhat = m / (1 - self.b1 ** t)
        vhat = v / (1 - self.b2 ** t)
        upd = mhat / (jnp.sqrt(vhat) + self.eps)
        if self.weight_decay and self.decoupled:
            upd = upd + self.weight_decay * p
        return (p - lr * upd).astype(p.dtype), (m, v)


class AdamW(Adam):
    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.01):
        super().__init__(lr, betas, eps, weight_decay)
        self.decoupled = True


class RMSProp(Optimizer):
    def __init__(self, lr=1e-2, rho=0.9, eps=1e-8, weight_decay=0.0):
        super().__init__(lr)
        self.rho, self.eps, self.weight_decay = rho, eps, weight_decay

    def init(self, params):
        return {n: jnp.zeros_like(p) for n, p in params.items()}

    def _init_slot(self, p):
        return jnp.zeros_like(p)

    def apply(self, step, name, p, g, slot):
        lr = self.sched(step)
        if self.weight_decay:
            g = g + self.weight_decay * p
        v = self.rho * slot + (1 - self.rho) * (g * g)
        return (p - lr * g / (jnp.sqrt(v) + self.eps)).astype(p.dtype), v


class AdaGrad(Optimizer):
    def __init__(self, lr=1e-2, eps=1e-8, weight_decay=0.0):
        super().__init__(lr)
        self.eps, self.weight_decay = eps, weight_decay

    def init(self, params):
        return {n: jnp.zeros_like(p) for n, p in params.items()}

    def _init_slot(self, p):
        return jnp.zeros_like(p)

    def apply(self, step, name, p, g, slot):
        lr = self.sched(step)
        if self.weight_decay:
            g = g + self.weight_decay * p
        acc = slot + g * g
        return (p - lr * g / (jnp.sqrt(acc) + self.eps)).astype(p.dtype), acc


class Adafactor(Optimizer):
    """Adafactor (Shazeer & Stern 2018) — the TPU-idiomatic
    memory-efficient optimizer for large models: the second moment of a
    (r, c) matrix parameter is stored as a rank-1 factorization (r + c
    floats instead of r*c), cutting optimizer HBM by ~dim/2 per matrix;
    f32 stats regardless of param dtype (bf16-safe).

    Modes mirror the T5 recipe:
      * ``lr=None`` (default): relative step size
        min(relative_step_cap, 1/sqrt(t)), usually combined with
        ``multiply_by_parameter_scale=True`` — no LR tuning needed;
      * explicit ``lr``: fixed/scheduled step size (set
        multiply_by_parameter_scale=False for optax-equivalent math —
        cross-validated against optax.adafactor in tests).

    ``momentum`` (beta1) adds back a full-size first moment — off by
    default, which is the memory win.  Factorization covers the last
    two axes when both are >= min_dim_size_to_factor; smaller or 1-D
    params keep a full second moment."""

    def __init__(self, lr=None, min_dim_size_to_factor=128,
                 decay_rate=0.8, multiply_by_parameter_scale=None,
                 clipping_threshold=1.0, momentum=None,
                 eps=(1e-30, 1e-3), weight_decay=0.0,
                 relative_step_cap=1e-2):
        super().__init__(0.0 if lr is None else lr)
        self.relative = lr is None
        if multiply_by_parameter_scale is None:
            multiply_by_parameter_scale = self.relative
        self.min_factor = int(min_dim_size_to_factor)
        self.decay_rate = float(decay_rate)
        self.param_scale = bool(multiply_by_parameter_scale)
        self.clip = clipping_threshold
        self.momentum = momentum
        self.eps1, self.eps2 = eps
        self.weight_decay = weight_decay
        self.relative_step_cap = relative_step_cap

    def _factored(self, p) -> bool:
        return (p.ndim >= 2
                and min(p.shape[-2], p.shape[-1]) >= self.min_factor)

    def init(self, params):
        return {n: self._init_slot(p) for n, p in params.items()}

    def _init_slot(self, p):
        if self._factored(p):
            slot = {"vr": jnp.zeros(p.shape[:-1], jnp.float32),
                    "vc": jnp.zeros(p.shape[:-2] + p.shape[-1:],
                                    jnp.float32)}
        else:
            slot = {"v": jnp.zeros(p.shape, jnp.float32)}
        if self.momentum:
            slot["m"] = jnp.zeros(p.shape, jnp.float32)
        return slot

    def apply(self, step, name, p, g, slot):
        t = (step + 1).astype(jnp.float32) if hasattr(step, "astype") \
            else float(step + 1)
        decay = 1.0 - t ** (-self.decay_rate)
        g32 = g.astype(jnp.float32)
        gsq = g32 * g32 + self.eps1
        new = {}
        if "vr" in slot:
            vr = decay * slot["vr"] + (1 - decay) * gsq.mean(-1)
            vc = decay * slot["vc"] + (1 - decay) * gsq.mean(-2)
            reduced = vr.mean(-1, keepdims=True)
            y = (g32 * jax.lax.rsqrt(vr / reduced)[..., None]
                 * jax.lax.rsqrt(vc)[..., None, :])
            new["vr"], new["vc"] = vr, vc
        else:
            v = decay * slot["v"] + (1 - decay) * gsq
            y = g32 * jax.lax.rsqrt(v)
            new["v"] = v
        if self.clip:
            rms_y = jnp.sqrt(jnp.mean(y * y))
            y = y / jnp.maximum(1.0, rms_y / self.clip)
        if self.relative:
            rho = jnp.minimum(self.relative_step_cap,
                              jax.lax.rsqrt(jnp.asarray(t, jnp.float32)))
        else:
            rho = self.sched(step)
        if self.param_scale:
            p32 = p.astype(jnp.float32)
            rho = rho * jnp.maximum(jnp.sqrt(jnp.mean(p32 * p32)),
                                    self.eps2)
        upd = rho * y
        if self.momentum:
            m = self.momentum * slot["m"] + (1 - self.momentum) * upd
            new["m"] = m
            upd = m
        if self.weight_decay:
            upd = upd + rho * self.weight_decay * p.astype(jnp.float32)
        return (p.astype(jnp.float32) - upd).astype(p.dtype), new

    def state_signature(self) -> str:
        return (f"Adafactor(f{self.min_factor},"
                f"m{self.momentum or 0})")

    def load_slot_arrays(self, slots: Dict[str, List]) -> None:
        """Rebuild the dict slots from the checkpoint's flat leaf lists.
        jax.tree flattens dicts in sorted-key order, so leaves arrive as
        ["m"?, "v"] or ["m"?, "vc", "vr"]."""
        est = {}
        for name, leaves in slots.items():
            arrs = [jnp.asarray(l) for l in leaves]
            if not arrs:
                est[name] = None
                continue
            slot = {}
            if self.momentum:
                slot["m"] = arrs[0]
                arrs = arrs[1:]
            if len(arrs) == 1:
                slot["v"] = arrs[0]
            elif len(arrs) == 2:
                slot["vc"], slot["vr"] = arrs
            else:
                raise ValueError(
                    f"unexpected Adafactor slot leaf count for {name!r}: "
                    f"{len(arrs)}")
            est[name] = slot
        self._eager_state = est


class GradAccum(Optimizer):
    """Gradient accumulation over `every` microbatches (beyond the
    reference surface; standard large-batch training on one chip).

    Each train step adds the microbatch gradient into an accumulator
    slot; every `every`-th step the wrapped optimizer applies the MEAN
    accumulated gradient and the accumulator resets.  Both paths are
    computed and `jnp.where`-selected, so the whole thing stays one
    compiled module with no data-dependent control flow — the
    accumulate-only steps cost elementwise work, not matmuls.

    The wrapped optimizer's schedule sees the number of *applied*
    updates (step // every), so LR decay is in optimizer-update units.
    Composes with DistOpt: DistOpt(GradAccum(SGD(...), 4)) allreduces
    each microbatch gradient, then accumulates the mean.

    Communication cost note: that nesting moves k allreduces per
    applied update over ICI — k times the bytes of an
    accumulate-locally-then-allreduce schedule.  It is the supported
    ordering because the executor emits the allreduce unconditionally
    each compiled step (a step-conditional collective inside the jitted
    module would need diverging comm schedules under one trace).  If
    the per-microbatch allreduce dominates, prefer cutting `every` and
    raising the per-step batch, or DistOpt(compress_dtype=...) /
    topk_ratio to shrink the per-step bytes instead."""

    def __init__(self, opt: Optimizer, every: int):
        super().__init__(opt.sched)
        if every < 1:
            raise ValueError(f"every must be >= 1, got {every}")
        self.opt = opt
        self.every = int(every)

    def init(self, params):
        base = self.opt.init(params)
        return {n: {"acc": jnp.zeros_like(p).astype(jnp.float32),
                    "base": base.get(n)}
                for n, p in params.items()}

    def _init_slot(self, p):
        return {"acc": jnp.zeros_like(p).astype(jnp.float32),
                "base": self.opt._init_slot(p)}

    def apply(self, step, name, p, g, slot):
        k = self.every
        acc = slot["acc"] + g.astype(jnp.float32)
        do_upd = (step % k) == (k - 1)
        upd_p, upd_base = self.opt.apply(step // k, name, p,
                                         (acc / k).astype(p.dtype),
                                         slot["base"])
        sel = lambda a, b: jnp.where(do_upd, a, b)
        new_p = sel(upd_p, p)
        new_base = jax.tree.map(sel, upd_base, slot["base"]) \
            if slot["base"] is not None else None
        new_acc = jnp.where(do_upd, jnp.zeros_like(acc), acc)
        return new_p, {"acc": new_acc, "base": new_base}

    def state_signature(self) -> str:
        return f"GradAccum({self.every})>{self.opt.state_signature()}"

    def load_slot_arrays(self, slots: Dict[str, List]) -> None:
        """Rebuild {"acc", "base"} dict slots from the checkpoint's flat
        leaf lists: leaf 0 is the accumulator; the rest reconstruct the
        WRAPPED optimizer's slot through ITS load_slot_arrays (so
        structured inner slots — e.g. a nested GradAccum — resume too).
        Both the eager path and the graph executor then see the
        structure GradAccum.apply needs."""
        heads, rests = {}, {}
        for name, leaves in slots.items():
            arrs = [jnp.asarray(l) for l in leaves]
            if not arrs:
                raise ValueError(
                    f"GradAccum slot for {name!r} is empty in checkpoint")
            heads[name] = arrs[0]
            rests[name] = arrs[1:]
        saved_inner = getattr(self.opt, "_eager_state", None)
        self.opt.load_slot_arrays(rests)
        inner = self.opt._eager_state
        self.opt._eager_state = saved_inner
        self._eager_state = {n: {"acc": heads[n], "base": inner.get(n)}
                             for n in heads}


# ---------------------------------------------------------------------------
# DistOpt — data-parallel wrapper; allreduce becomes an in-graph XLA
# collective over the 'data' mesh axis (BASELINE.json:5)
# ---------------------------------------------------------------------------

#: DistOpt gradient-compression modes with first-class optimizer state
#: (error-feedback residuals); `compress_dtype` keeps covering the
#: stateless casts/quantizers
_COMPRESSION_MODES = ("int8_ring",)


class DistOpt(Optimizer):
    """Wraps a base optimizer with gradient synchronization.

    Graph mode (the production path): the model's compiled step runs under
    shard_map over the global mesh; ``reduce_gradients`` emits
    ``lax.pmean`` which XLA lowers to a single fused all-reduce over ICI.
    Variants mirroring the reference Communicator:
      * fp16/bf16-compressed allreduce  (`backward_and_update_half`)
      * top-K sparsified allreduce      (`backward_and_update_partial`,
        fixed-K all-gather formulation — XLA-friendly; SURVEY.md §7.3.4)

    ``compression="int8_ring"`` is the production byte-reduction mode
    (EQuARX-style blockwise-int8 ring RS+AG, ~4x fewer wire bytes) with
    **error-feedback accumulation**: a per-parameter, PER-RANK f32
    residual rides the optimizer slots as ``{"base": <inner slot>,
    "ef": (world, *param.shape) residual}``, is added to the gradient
    before quantization and refilled with the quantization error after
    decode.  Because it is ordinary optimizer state, the graph executor
    donates it and shards it over the data axis (each rank physically
    owns its slice — the cross-replica 1/N layout), and checkpoints
    carry EVERY rank's residual — kill-and-resume stays bitwise
    including the residuals.  The decode is bitwise deterministic
    (communicator contract: fixed block order, fixed per-hop requantize
    grids, consensus scales).  See docs/parallelism.md "Quantized
    gradient sync"."""

    def __init__(self, opt: Optimizer, nccl_id=None, local_rank: int = 0,
                 world_size: Optional[int] = None, data_axis: str = "data",
                 compress_dtype=None, topk_ratio: float = 0.0,
                 shard_weight_update: bool = False,
                 compression: Optional[str] = None,
                 error_feedback: bool = True,
                 compression_block: int = 256):
        super().__init__(opt.sched)
        self.opt = opt
        self.data_axis = data_axis
        self.compress_dtype = compress_dtype
        self.topk_ratio = topk_ratio
        self.local_rank = local_rank
        self._world_size = world_size
        if compression is not None and compression not in _COMPRESSION_MODES:
            raise ValueError(
                f"unknown compression mode {compression!r} "
                f"(known: {_COMPRESSION_MODES})")
        if compression is not None and (compress_dtype is not None
                                        or topk_ratio):
            raise ValueError(
                "compression= is exclusive with compress_dtype=/"
                "topk_ratio= — pick one gradient-sync variant")
        self.compression = compression
        self.error_feedback = bool(error_feedback)
        self.compression_block = int(compression_block)
        # ZeRO-1 / cross-replica weight-update sharding (beyond the
        # reference Communicator; PAPERS.md "Automatic Cross-Replica
        # Sharding of Weight Update in Data-Parallel Training"): the
        # graph executor shards optimizer moments over the data axis and
        # lets GSPMD partition the update, so slot HBM scales 1/N
        self.shard_weight_update = shard_weight_update
        del nccl_id  # reference-API compat; bootstrap is PJRT-side

    @property
    def world_size(self) -> int:
        if self._world_size is not None:
            return self._world_size
        from .parallel import mesh as mesh_mod
        m = mesh_mod.current_mesh()
        if m is not None and self.data_axis in m.shape:
            return m.shape[self.data_axis]
        return 1

    # functional core delegates to the wrapped optimizer; under
    # compression="int8_ring" it wraps every slot as
    # {"base": <inner slot>, "ef": f32 residual} so the error-feedback
    # state is ordinary donated/sharded/checkpointed optimizer state.
    #
    # The residual is PER-RANK state (each rank accumulates the
    # quantization error of ITS OWN wire contribution), so its global
    # shape is (world, *param.shape) and the graph executor shards it
    # over the data axis — each rank physically owns exactly its slice
    # (the ZeRO-style 1/N layout, arXiv:2004.13336, applied to the
    # residual).  Declaring it replicated instead would be a
    # correctness bug, not just waste: the per-device copies diverge by
    # construction, a checkpoint would capture rank 0's copy for
    # everyone, and kill-and-resume would silently change the
    # trajectory (caught by the bitwise resume test).
    def init(self, params):
        base = self.opt.init(params)
        if self.compression is None:
            return base
        w = max(1, self.world_size)
        return {n: {"base": base.get(n),
                    "ef": jnp.zeros((w,) + tuple(p.shape), jnp.float32)}
                for n, p in params.items()}

    def _init_slot(self, p):
        inner = self.opt._init_slot(p)
        if self.compression is None:
            return inner
        w = max(1, self.world_size)
        return {"base": inner,
                "ef": jnp.zeros((w,) + tuple(p.shape), jnp.float32)}

    def apply(self, step, name, p, g, slot):
        if self.compression is None:
            return self.opt.apply(step, name, p, g, slot)
        # `g` arrives already synced (reduce_gradients wrote the fresh
        # residual into the slot); the inner update runs on the base half
        new_p, new_base = self.opt.apply(step, name, p, g, slot["base"])
        return new_p, {"base": new_base, "ef": slot["ef"]}

    def reduce_gradients(self, grads: Dict[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
        """Mean-allreduce gradients over the data axis (in-graph).

        Called by the graph executor *inside* shard_map; if no mesh axis is
        bound (single-process eager), this is the identity.

        Under ``compression="int8_ring"`` each gradient rides the
        error-feedback int8 ring: the slot's f32 residual is added
        before quantization and refilled with the decode's quantization
        error (written back into ``self._eager_state`` — inside the
        compiled step that IS the slots pytree the executor returns, so
        the residual is donated state like any moment).  With
        ``error_feedback=False`` the residual stays zero (the parity
        test documents why that loses).

        Telemetry: an ``opt.grad_sync`` span (trace-time when called
        under the compiled step), the communicator's per-op payload
        counters, and the ``comm.wire_bytes.compressed`` /
        ``.f32_equiv`` counter pair (obs.events).  With a runtime-
        attribution ledger installed (obs.attr) the EAGER path is
        additionally timed under the ``grad_sync`` key — only when the
        gradients are concrete: under a compiled step this function
        runs at trace time, where a wall clock would measure tracing,
        not the collective (the in-graph sync is then attributed to
        the enclosing ``train_step_dp2*`` dispatch instead)."""
        import time

        from .obs import attr as obs_attr
        from .obs import events as obs_events
        from .parallel import communicator as comm
        led = obs_attr.get()
        if led is not None and any(
                isinstance(g, jax.core.Tracer) for g in grads.values()):
            led = None
        with obs_events.span("opt.grad_sync", axis=self.data_axis,
                             tensors=len(grads),
                             compression=self.compression or "none"):
            t0 = time.perf_counter() if led is not None else 0.0
            if self.compression == "int8_ring":
                out = self._reduce_int8_ring(grads)
            else:
                out = comm.allreduce_grads(
                    grads, axis=self.data_axis,
                    compress_dtype=self.compress_dtype,
                    topk_ratio=self.topk_ratio)
            if led is not None:
                led.note("grad_sync", time.perf_counter() - t0)
            return out

    def _reduce_int8_ring(self, grads: Dict[str, jnp.ndarray]
                          ) -> Dict[str, jnp.ndarray]:
        from .parallel import communicator as comm
        est = getattr(self, "_eager_state", None)
        if est is None:
            est = self._eager_state = {}
        # under the compiled step's shard_map the ef slot arrives as this
        # rank's (1, *shape) slice of the (world, *shape) global; [0]
        # peels the rank axis, [None] restores it on the write-back
        bound = comm.axis_bound(self.data_axis)
        out = {}
        for name, g in grads.items():
            if g is None:
                out[name] = None
                continue
            slot = est.get(name)
            has_ef = (isinstance(slot, dict) and "ef" in slot
                      and self.error_feedback)
            res = (slot["ef"][0] if has_ef
                   else jnp.zeros((), jnp.float32))  # scalar 0 broadcasts
            synced, new_res = comm.ef_quantized_allreduce(
                g, res, axis=self.data_axis, block=self.compression_block)
            if has_ef and bound:
                est[name] = dict(slot, ef=new_res[None])
            out[name] = synced
        return out

    # -- reference API surface ------------------------------------------------
    def __call__(self, loss: Tensor) -> None:
        """`opt(loss)` must sync gradients exactly like backward_and_update —
        regression guard: the base-class __call__ skips reduce_gradients."""
        self.backward_and_update(loss)

    def backward_and_update(self, loss: Tensor) -> None:
        pg = autograd.backward(loss)
        if self.compression is not None:
            # the error-feedback slots live in DistOpt's OWN store (the
            # executor's slots pytree under the trace): make sure every
            # param has one BEFORE the sync, so the residual written by
            # reduce_gradients lands in persistent state
            if getattr(self, "_eager_state", None) is None:
                self._eager_state = {}
            est = self._eager_state
            for p, _ in pg:
                n = p.name or str(id(p))
                if est.get(n) is None:
                    est[n] = self._init_slot(p.data)
        grads = {(p.name or str(id(p))): g.data for p, g in pg}
        grads = self.reduce_gradients(grads)
        for p, _ in pg:
            g = grads[(p.name or str(id(p)))]
            gt = Tensor(data=g, device=p.device, requires_grad=False)
            if self.compression is not None:
                # route through DistOpt's own apply (unwraps {"base","ef"});
                # the inner optimizer's eager store never sees wrapped slots
                Optimizer.update(self, p, gt)
            else:
                self.opt.update(p, gt)
        self.opt.step()
        self.step_counter = self.opt.step_counter

    def backward_and_update_half(self, loss: Tensor) -> None:
        """One bf16-compressed sync (reference surface).  The previous
        compress_dtype is RESTORED afterwards — this call must not
        silently leave every later backward_and_update compressed."""
        saved = self.compress_dtype
        self.compress_dtype = jnp.bfloat16
        try:
            self.backward_and_update(loss)
        finally:
            self.compress_dtype = saved

    def backward_and_partial_update(self, loss: Tensor, topk_ratio: float = 0.01) -> None:
        """One top-K sparsified sync (reference surface); the previous
        topk_ratio is restored afterwards (same contract as
        :meth:`backward_and_update_half`)."""
        saved = self.topk_ratio
        self.topk_ratio = topk_ratio
        try:
            self.backward_and_update(loss)
        finally:
            self.topk_ratio = saved

    def update(self, param: Tensor, grad: Tensor) -> None:
        if self.compression is not None:
            Optimizer.update(self, param, grad)
            return
        self.opt.update(param, grad)

    def step(self) -> None:
        self.opt.step()
        self.step_counter = self.opt.step_counter

    def set_states(self, s: Dict) -> None:
        super().set_states(s)
        self.opt.set_states(s)

    def state_signature(self) -> str:
        if self.compression is not None:
            # the {"base","ef"} wrapping IS extra slot structure: a
            # restore across compression on/off must be rejected, not
            # have a residual reinterpreted as a moment (or vice versa)
            return f"EF({self.compression})>{self.opt.state_signature()}"
        # without compression DistOpt adds no slot structure of its own
        return self.opt.state_signature()

    def slot_arrays(self) -> Dict[str, List]:
        if self.compression is not None:
            # wrapped slots are canonical in DistOpt's own store (the
            # executor mirrors compiled-step slots there) — leaves land
            # as [<base leaves...>, ef] (sorted-key flatten order)
            return Optimizer.slot_arrays(self)
        # eager updates fill the inner opt's store; the graph executor
        # mirrors into both — prefer whichever is populated
        if getattr(self.opt, "_eager_state", None):
            return self.opt.slot_arrays()
        return super().slot_arrays()

    def load_slot_arrays(self, slots: Dict[str, List]) -> None:
        if self.compression is not None:
            # inverse of the wrapped flatten: the LAST leaf is the f32
            # error-feedback residual ("base" < "ef" in sorted-key
            # order); the rest rebuild the inner optimizer's slot
            # through ITS load_slot_arrays (structured slots — e.g. a
            # wrapped GradAccum — resume too), exactly like GradAccum
            efs, rests = {}, {}
            for name, leaves in slots.items():
                arrs = [jnp.asarray(l) for l in leaves]
                if not arrs:
                    raise ValueError(
                        f"compressed DistOpt slot for {name!r} is empty "
                        f"in checkpoint (missing error-feedback residual)")
                efs[name] = arrs[-1].astype(jnp.float32)
                rests[name] = arrs[:-1]
            saved_inner = getattr(self.opt, "_eager_state", None)
            self.opt.load_slot_arrays(rests)
            inner = self.opt._eager_state
            self.opt._eager_state = saved_inner
            self._eager_state = {n: {"base": inner.get(n), "ef": efs[n]}
                                 for n in efs}
            return
        self.opt.load_slot_arrays(slots)
        self._eager_state = self.opt._eager_state
