"""examples/llama/serve_demo.py — continuous-batching serving demo.

Drives a mixed prompt-length request stream through
`singa_tpu.serve.ServeEngine` on a small Llama config, streaming tokens
per request, exercising deadlines and queue backpressure, and printing
the engine's metric snapshot.  Runs on CPU in under a minute:

    python examples/llama/serve_demo.py
    python examples/llama/serve_demo.py --requests 16 --slots 4 \
        --obs /tmp/serve_events.jsonl        # JSONL telemetry stream
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402


def main():
    p = argparse.ArgumentParser(description="continuous-batching demo")
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--max-len", type=int, default=96)
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--shared-prefix", type=int, default=16,
                   help="tokens of a shared system prompt prepended "
                        "to every request (0 = fully private prompts)")
    p.add_argument("--requests", type=int, default=10)
    p.add_argument("--new-tokens", type=int, default=24)
    p.add_argument("--deadline", type=float, default=30.0,
                   help="per-request deadline (s)")
    p.add_argument("--obs", default="",
                   help="JSONL telemetry sink path (SINGA_OBS)")
    args = p.parse_args()
    if args.shared_prefix + 3 + args.new_tokens > args.max_len:
        p.error(f"--shared-prefix {args.shared_prefix} + a >=3-token "
                f"private suffix + --new-tokens {args.new_tokens} "
                f"exceeds --max-len {args.max_len}")
    if args.obs:
        os.environ["SINGA_OBS"] = args.obs

    from singa_tpu import models, serve, tensor

    tensor.set_seed(0)
    np.random.seed(0)
    cfg = models.LlamaConfig.tiny()
    m = models.Llama(cfg)
    m.eval()
    m.compile([tensor.from_numpy(np.zeros((1, 4), np.int32))],
              is_train=False, use_graph=False)

    print(f"engine: {args.slots} block-table rows x {args.max_len} "
          f"positions, paged in {args.block_size}-token blocks",
          flush=True)
    t0 = time.time()
    eng = serve.ServeEngine(m, args.slots, args.max_len,
                            block_size=args.block_size,
                            heartbeat_timeout_s=120.0)
    # warm the two compiled programs before the traffic
    eng.submit(np.zeros(4, np.int32), max_new_tokens=2)
    eng.run_until_idle()
    print(f"warmup (2 compiled programs): {time.time() - t0:.1f}s",
          flush=True)

    rng = np.random.RandomState(42)
    max_private = args.max_len - args.new_tokens - args.shared_prefix
    lens = rng.randint(3, max(4, min(max_private + 1,
                                     max_private // 2 + 2)),
                       size=args.requests)
    shared = rng.randint(0, cfg.vocab_size,
                         (args.shared_prefix,)).astype(np.int32)
    handles = []
    t0 = time.time()
    for i, plen in enumerate(lens):
        prompt = np.concatenate([
            shared,
            rng.randint(0, cfg.vocab_size, (plen,)).astype(np.int32)])

        def stream(tok, h, i=i):
            if len(h.tokens) == 1:
                print(f"  req{i:02d} first token after "
                      f"{h.ttft_s * 1e3:.0f} ms", flush=True)

        try:
            handles.append(eng.submit(
                prompt, max_new_tokens=args.new_tokens,
                deadline_s=args.deadline, on_token=stream))
        except serve.QueueFull:
            print(f"  req{i:02d} REJECTED (queue full — backpressure)",
                  flush=True)
        # a few engine ticks between arrivals: requests overlap, slots
        # churn, prefill interleaves with decode
        if i % 3 == 2:
            eng.step()
    eng.run_until_idle()
    dt = time.time() - t0

    n_tok = sum(len(h.tokens) for h in handles)
    print(f"\nserved {len(handles)} requests, {n_tok} tokens "
          f"in {dt:.2f}s ({n_tok / dt:.0f} tok/s)", flush=True)
    for i, h in enumerate(handles):
        out = h.result()
        print(f"  req{i:02d} [{h.finish_reason:8s}] "
              f"{len(h.tokens):3d} tokens: {out[:6]}...", flush=True)
    snap = eng.metrics.snapshot()
    print(f"\nmetrics: admitted {snap['admitted']}, rejected "
          f"{snap['rejected']}, evicted {snap['evicted']}", flush=True)
    print(f"prefix cache: {snap['prefix_hits']} hits, "
          f"{snap['prefix_hit_tokens']} prompt tokens served without "
          f"prefill", flush=True)
    if snap["ttft_ms"]:
        print(f"TTFT p50 {snap['ttft_ms']['p50']:.1f} ms, "
              f"p99 {snap['ttft_ms']['p99']:.1f} ms; per-token p50 "
              f"{snap['token_ms']['p50']:.2f} ms", flush=True)
    print(f"compiled programs (prefill, decode): {eng.compiled_counts()}",
          flush=True)
    if args.obs:
        print(f"telemetry stream: {args.obs}", flush=True)


if __name__ == "__main__":
    main()
