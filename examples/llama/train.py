"""examples/llama — Llama-3 training with multi-axis GSPMD sharding
(BASELINE.json:11: "Llama-3-8B ... sharded across a v4-32 pod, stretch
goal").

Parallelism is declared as a mesh (DP x TP x SP); the graph executor
shards params/batch by the model's SHARD_RULES and XLA inserts the
collectives over ICI.  On a CPU box, `--force-host-devices 8` builds a
virtual 8-device mesh so the full sharded step compiles and runs.

    python examples/llama/train.py --preset tiny --dp 2 --tp 2 --sp 2 \
        --force-host-devices 8
    python examples/llama/train.py --preset 8b --dp 4 --tp 8   # pod slice
"""

import argparse
import time

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np  # noqa: E402

# importing common pins the cpu backend when --device cpu was passed
import common  # noqa: E402,F401


def main():
    p = argparse.ArgumentParser(description="Llama training (GSPMD sharded)")
    p.add_argument("--device", default="auto", choices=["auto", "cpu", "tpu"],
                   help="cpu pins the host backend before JAX init")
    p.add_argument("--preset", default="tiny", choices=["tiny", "small", "8b"])
    p.add_argument("--dp", type=int, default=1, help="data-parallel ways")
    p.add_argument("--tp", type=int, default=1, help="tensor-parallel ways")
    p.add_argument("--sp", type=int, default=1, help="sequence-parallel ways")
    p.add_argument("--pp", type=int, default=1,
                   help="pipeline-parallel stages (GPipe over the "
                        "'pipe' mesh axis; layers must divide evenly)")
    p.add_argument("--micro", type=int, default=0,
                   help="pipeline microbatches (default: = --pp)")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=128)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--lr", type=float, default=None,
                   help="default 3e-4; with --opt adafactor, unset "
                        "means the relative-step schedule")
    p.add_argument("--force-host-devices", type=int, default=0,
                   help="virtual CPU devices for meshes without hardware")
    p.add_argument("--window", type=int, default=0,
                   help="sliding-window attention (Mistral-style; "
                        "chunked O(T*W) path for long sequences)")
    p.add_argument("--experts", type=int, default=0,
                   help="Mixtral-style MoE: SwiGLU experts per block "
                        "(use with --ep ways via the 'expert' axis)")
    p.add_argument("--ep", type=int, default=1, help="expert-parallel ways")
    p.add_argument("--opt", default="adamw",
                   choices=["adamw", "adafactor", "sgd"],
                   help="adafactor = factored second moment (r+c floats "
                        "per matrix instead of r*c) with relative step "
                        "size — the big-model TPU recipe")
    p.add_argument("--int8-ring", action="store_true",
                   help="int8-ring quantized gradient sync with error "
                        "feedback (DistOpt compression='int8_ring'; "
                        "pays off on slow inter-host links — see "
                        "docs/parallelism.md)")
    p.add_argument("--zero1", action="store_true",
                   help="ZeRO-1 weight-update sharding: optimizer "
                        "moments sharded over the data axis (1/N HBM)")
    p.add_argument("--fused-loss", action="store_true",
                   help="chunked fused lm-head+CE (no (B*T,V) logits; "
                        "train_one_batch returns (loss, loss))")
    p.add_argument("--plan", action="store_true",
                   help="shape-only capacity plan (no weights allocated): "
                        "per-device param/moment/grad bytes + HBM fit")
    p.add_argument("--generate", type=int, default=0, metavar="N",
                   help="after training, greedy-generate N tokens with "
                        "the KV cache")
    args = p.parse_args()

    if args.force_host_devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.force_host_devices}"
        ).strip()
        import jax
        jax.config.update("jax_platforms", "cpu")

    from singa_tpu import models, opt, parallel, tensor

    presets = {
        "tiny": models.LlamaConfig.tiny,
        "small": models.LlamaConfig.small,
        "8b": models.LlamaConfig.llama3_8b,
    }
    cfg = presets[args.preset]()
    if args.fused_loss:
        cfg.fused_loss = True
    if args.pp > 1:
        cfg.pipeline_stages = args.pp
        cfg.pipeline_microbatches = args.micro
    if args.window:
        if args.window < 1:
            p.error(f"--window must be positive, got {args.window}")
        if args.sp > 1:
            p.error("--window does not compose with --sp (ring attention)")
        cfg.sliding_window = args.window
    if args.experts:
        cfg.num_experts = args.experts
        cfg.moe_top_k = min(cfg.moe_top_k, args.experts)
    if args.ep > 1:
        if not args.experts:
            p.error("--ep needs --experts (an 'expert' axis with no MoE "
                    "replicates weights and wastes devices)")
        if args.experts % args.ep:
            p.error(f"--experts {args.experts} must divide by --ep "
                    f"{args.ep} (otherwise expert weights silently "
                    "replicate instead of sharding)")

    axes = {k: v for k, v in
            (("data", args.dp), ("model", args.tp), ("seq", args.sp),
             ("pipe", args.pp), ("expert", args.ep))
            if v > 1} or {"data": 1}
    mesh = parallel.make_mesh(axes)
    parallel.set_mesh(mesh)
    print(f"mesh axes: {axes}  devices: {mesh.devices.size}")

    if args.plan:
        import jax
        import jax.numpy as jnp
        plan_lr = 3e-4 if args.lr is None else args.lr
        plan_opt = (opt.DistOpt(opt.AdamW(lr=plan_lr),
                                shard_weight_update=True)
                    if args.zero1 else opt.AdamW(lr=plan_lr))
        plan = parallel.plan_train_step(
            models.Llama(cfg), plan_opt,
            (jax.ShapeDtypeStruct((args.batch, args.seq), jnp.int32),),
            mesh=mesh)
        gib = 2.0 ** 30
        print(f"params (global):     {plan.param_bytes_global / gib:8.2f} GiB")
        print(f"params / device:     {plan.param_bytes_per_device / gib:8.2f} GiB")
        print(f"moments / device:    {plan.slot_bytes_per_device / gib:8.2f} GiB")
        print(f"grads / device:      {plan.grad_bytes_per_device / gib:8.2f} GiB")
        print(f"state / device:      {plan.per_device_state_bytes / gib:8.2f} GiB")
        for chip in ("v4", "v5e", "v5p"):
            print(f"fits {chip:4s} (75% HBM): {plan.fits(chip)}")
        parallel.set_mesh(None)
        return

    tensor.set_seed(0)
    m = models.Llama(cfg)
    lr = 3e-4 if args.lr is None else args.lr
    base_opt = {"adamw": lambda: opt.AdamW(lr=lr),
                # explicit --lr overrides adafactor's relative step
                "adafactor": lambda: opt.Adafactor(lr=args.lr),
                "sgd": lambda: opt.SGD(lr=lr, momentum=0.9),
                }[args.opt]()
    m.set_optimizer(opt.DistOpt(
        base_opt, shard_weight_update=args.zero1,
        compression="int8_ring" if args.int8_ring else None))
    vocab = min(cfg.vocab_size, 32000)
    ids_np = np.random.RandomState(0).randint(
        0, vocab, (args.batch, args.seq)).astype(np.int32)
    ids = tensor.from_numpy(ids_np)
    print(f"params: {m.num_params() / 1e6:.1f}M; compiling sharded step ...")
    m.compile([ids], is_train=True, use_graph=True)

    flops_step = m.flops_per_token(args.seq) * args.batch * args.seq
    for step in range(args.steps):
        t0 = time.perf_counter()
        _, loss = m.train_step(ids)
        lv = float(np.asarray(loss.data))
        dt = time.perf_counter() - t0
        tok_s = args.batch * args.seq / dt
        print(f"step {step}: loss {lv:.4f}  {tok_s:,.0f} tok/s  "
              f"{flops_step / dt / 1e12:.2f} TFLOP/s")

    if args.generate:
        # KV-cache decoding: compiled prefill + one compiled decode step
        parallel.set_mesh(None)
        prompt = ids_np[:1, : min(8, args.seq)]
        m.generate(prompt, args.generate)     # warm: compile prefill+decode
        t0 = time.perf_counter()
        out = m.generate(prompt, args.generate)
        dt = time.perf_counter() - t0
        print(f"generated {args.generate} tokens "
              f"({args.generate / dt:.1f} tok/s, cached decode): "
              f"{out[0, prompt.shape[1]:].tolist()}")

    parallel.set_mesh(None)


if __name__ == "__main__":
    main()
