"""examples/mlp/resume_demo — crash-consistent training on CPU.

The fault-tolerance subsystem (singa_tpu/train, docs/training.md) in
one runnable file:

    python examples/mlp/resume_demo.py --steps 60 --crash-at 25
    # ... trains, checkpoints every --save-every, dies hard at step 25
    python examples/mlp/resume_demo.py --steps 60
    # ... resumes from the newest commit and finishes the run

Ctrl-C / SIGTERM at any point also checkpoints and exits cleanly (the
preemption path). `python tools/ckpt_fsck.py <ckpt-dir>` audits the
checkpoint directory afterwards.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

from common import _pin_cpu_backend_if_requested  # noqa: E402,F401

import numpy as np  # noqa: E402

from singa_tpu import models, opt, tensor  # noqa: E402
from singa_tpu.train import AsyncCheckpointManager, TrainRunner  # noqa: E402
from singa_tpu.utils.data import DataLoader, synthetic_dataset  # noqa: E402


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=60, help="total run length")
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--save-every", type=int, default=5)
    p.add_argument("--ckpt-dir", default="ckpts_resume_demo")
    p.add_argument("--crash-at", type=int, default=None,
                   help="simulate a hard kill (os._exit) after this step")
    p.add_argument("--record", action="store_true",
                   help="append the train_run record to runs/records.jsonl")
    args = p.parse_args()

    np.random.seed(0)
    tensor.set_seed(0)
    x, y = synthetic_dataset("blobs", n=512, classes=10, shape=(64,))
    loader = DataLoader(x, y, batch_size=args.batch_size, seed=1,
                        drop_last=True, use_native=False)

    m = models.MLP(perceptron_size=(64,), num_classes=10)
    m.set_optimizer(opt.Adam(lr=1e-3))
    m.compile([tensor.from_numpy(x[:args.batch_size])], is_train=True,
              use_graph=True)

    losses = []

    def on_step(step, outs):
        losses.append(float(outs[1].to_numpy()))
        if step % 10 == 0:
            print(f"step {step:4d}  loss {losses[-1]:.4f}")
        if args.crash_at is not None and step == args.crash_at:
            print(f"*** simulating hard crash (kill -9) at step {step} — "
                  f"rerun without --crash-at to resume", flush=True)
            os._exit(1)   # no cleanup, no final save: the crash case

    runner = TrainRunner(
        m, loader, total_steps=args.steps,
        ckpt=AsyncCheckpointManager(args.ckpt_dir, keep_last=3,
                                    keep_every=20,
                                    save_every=args.save_every),
        step_timeout=300.0, on_step=on_step,
        record_store=os.path.join("runs", "records.jsonl")
        if args.record else None,
        on_fatal=lambda msg: (_ for _ in ()).throw(SystemExit(msg)))
    with runner:
        res = runner.run()
    resumed = (f"resumed from step {res.resumed_from}"
               if res.resumed_from >= 0 else "fresh start")
    print(f"{res.outcome}: {res.steps}/{args.steps} steps ({resumed}), "
          f"{res.ckpt_count} checkpoint(s), {res.wall_s:.2f}s wall; "
          f"final loss {losses[-1] if losses else float('nan'):.4f}")
    print(f"checkpoints in {args.ckpt_dir}/ — audit with: "
          f"python tools/ckpt_fsck.py {args.ckpt_dir}")


if __name__ == "__main__":
    main()
