"""examples/mlp — the reference smoke workload (BASELINE.json:7:
"examples/mlp MNIST eager CppCPU parity smoke").

    python examples/mlp/train.py                    # synthetic MNIST shapes
    python examples/mlp/train.py --device tpu       # one-line device change
    python examples/mlp/train.py --no-graph         # eager (debug) mode
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

from common import base_parser, dataset_arrays, train_classifier  # noqa: E402

from singa_tpu import models  # noqa: E402


def main():
    p = base_parser("MLP on MNIST (reference examples/mlp)")
    p.add_argument("--hidden", type=int, nargs="+", default=[100])
    p.add_argument("--dataset", default="mnist")
    args = p.parse_args()
    xt, yt, xe, ye, classes, _ = dataset_arrays(args.dataset, args.data_dir)
    m = models.MLP(perceptron_size=tuple(args.hidden), num_classes=classes)
    train_classifier(m, args, xt, yt, xe, ye)


if __name__ == "__main__":
    main()
