"""examples/onnx/bert — BERT-base through the sonnx path
(BASELINE.json:9: "ONNX BERT-base ... inference via sonnx import").

With no network egress we can't fetch the official ONNX zoo file, so the
script (a) loads `--onnx <path>` when given one, else (b) builds a BERT
with our model zoo, EXPORTS it to ONNX with sonnx, reimports, and checks
import==native — which exercises the identical import path an official
file takes.

    python examples/onnx/bert.py                    # self-exported round-trip
    python examples/onnx/bert.py --onnx bert.onnx   # a real exported file
    python examples/onnx/bert.py --device tpu --compile
"""

import argparse
import time

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np  # noqa: E402

# importing common pins the cpu backend when --device cpu was passed
import common  # noqa: E402,F401

import singa_tpu as singa
from singa_tpu import models, sonnx
from singa_tpu.tensor import Tensor


def main():
    p = argparse.ArgumentParser(description="BERT via sonnx")
    p.add_argument("--onnx", default="", help="path to a BERT .onnx file")
    p.add_argument("--device", default="auto", choices=["auto", "cpu", "tpu"])
    p.add_argument("--layers", type=int, default=2, help="(self-export mode)")
    p.add_argument("--dim", type=int, default=128)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--vocab", type=int, default=1000)
    p.add_argument("--batch", type=int, default=2)
    p.add_argument("--seq", type=int, default=64)
    p.add_argument("--compile", action="store_true",
                   help="compile the imported graph to one XLA module")
    p.add_argument("--finetune", type=int, default=0, metavar="STEPS",
                   help="fine-tune the IMPORTED model for N steps "
                        "(training-capable import: the reimported graph "
                        "trains through the compiled executor)")
    args = p.parse_args()

    dev = singa.device.create_device(args.device)
    singa.device.set_default_device(dev)
    ids = np.random.RandomState(0).randint(
        0, args.vocab, (args.batch, args.seq)).astype(np.int64)
    t_ids = Tensor(data=ids, device=dev)

    ref_logits = None
    if args.onnx:
        model_proto = sonnx.load(args.onnx)
    else:
        cfg = models.BERTConfig(vocab_size=args.vocab, dim=args.dim,
                                num_heads=args.heads, num_layers=args.layers,
                                max_position=max(128, args.seq), dropout=0.0)
        native = models.BERT(cfg)
        hidden, _pooled = native(t_ids)
        ref_logits = np.asarray(hidden.data)
        print("exporting BERT to ONNX via sonnx.to_onnx ...")
        model_proto = sonnx.to_onnx(native, [t_ids])
        n_nodes = len(model_proto.graph.node)
        n_init = len(model_proto.graph.initializer)
        print(f"  graph: {n_nodes} nodes, {n_init} initializers")

    print("importing with sonnx.prepare ...")
    rep = sonnx.prepare(model_proto, device=dev)
    if args.compile:
        rep.compile([t_ids], is_train=False, use_graph=True)
    t0 = time.perf_counter()
    outs = rep.run([t_ids])
    lat = time.perf_counter() - t0
    out = np.asarray(outs[0].data)
    print(f"encoder output shape {out.shape}  "
          f"first-call latency {lat * 1e3:.1f} ms")
    if ref_logits is not None:
        err = np.max(np.abs(out - ref_logits))
        print(f"import vs native max |diff| = {err:.2e}")
        assert err < 1e-2, "sonnx round-trip mismatch"
        print("round-trip OK")

    if args.finetune:
        from singa_tpu import autograd, opt
        rep.set_optimizer(opt.AdamW(lr=3e-4))
        rep.set_loss(lambda outs, y: autograd.mse_loss(
            outs[0] if isinstance(outs, (list, tuple)) else outs, y))
        target = Tensor(data=np.zeros_like(out), device=dev,
                        requires_grad=False)
        rep.compile([t_ids], is_train=True, use_graph=True)
        for step in range(args.finetune):
            _, loss = rep.train_step(t_ids, target)
            print(f"finetune step {step}: loss {float(loss.to_numpy()):.4f}")


if __name__ == "__main__":
    main()
