"""examples/onnx/gpt2 — GPT-2 through the sonnx path + greedy generation
(BASELINE.json:9: "GPT-2 ... inference via sonnx import").

Like bert.py: imports `--onnx <path>` if given, else exports our zoo
GPT-2 and reimports it.  Generation re-runs the imported graph at a
fixed sequence length (static shapes — the XLA-friendly formulation)
with left-padding, taking the logits at the last real position.

    python examples/onnx/gpt2.py --steps 8
    python examples/onnx/gpt2.py --onnx gpt2.onnx --device tpu
"""

import argparse
import time

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np  # noqa: E402

# importing common pins the cpu backend when --device cpu was passed
import common  # noqa: E402,F401

import singa_tpu as singa
from singa_tpu import models, sonnx
from singa_tpu.tensor import Tensor


def main():
    p = argparse.ArgumentParser(description="GPT-2 via sonnx + generation")
    p.add_argument("--onnx", default="", help="path to a GPT-2 .onnx file")
    p.add_argument("--device", default="auto", choices=["auto", "cpu", "tpu"])
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--dim", type=int, default=128)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--vocab", type=int, default=1000)
    p.add_argument("--seq", type=int, default=32)
    p.add_argument("--steps", type=int, default=8, help="tokens to generate")
    args = p.parse_args()

    dev = singa.device.create_device(args.device)
    singa.device.set_default_device(dev)

    rng = np.random.RandomState(0)
    prompt = rng.randint(0, args.vocab, (1, args.seq // 2)).astype(np.int64)

    if args.onnx:
        model_proto = sonnx.load(args.onnx)
        ref = None
    else:
        cfg = models.GPT2Config(vocab_size=args.vocab, dim=args.dim,
                                num_heads=args.heads, num_layers=args.layers,
                                max_position=max(64, args.seq), dropout=0.0)
        native = models.GPT2(cfg)
        full = np.zeros((1, args.seq), np.int64)
        full[0, :prompt.shape[1]] = prompt
        t_full = Tensor(data=full, device=dev)
        ref = np.asarray(native(t_full).data)
        print("exporting GPT-2 to ONNX via sonnx.to_onnx ...")
        model_proto = sonnx.to_onnx(native, [t_full])
        print(f"  graph: {len(model_proto.graph.node)} nodes")

    rep = sonnx.prepare(model_proto, device=dev)

    ids = np.zeros((1, args.seq), np.int64)
    n = prompt.shape[1]
    ids[0, :n] = prompt
    t_ids = Tensor(data=ids, device=dev)
    if ref is not None:
        (logits,) = rep.run([t_ids])
        err = np.max(np.abs(np.asarray(logits.data) - ref))
        print(f"import vs native max |diff| = {err:.2e}")
        assert err < 1e-2

    print(f"greedy generation, {args.steps} tokens:")
    t0 = time.perf_counter()
    for _ in range(args.steps):
        if n >= args.seq:
            break
        t_ids.copy_from(ids)
        (logits,) = rep.run([t_ids])
        nxt = int(np.asarray(logits.data)[0, n - 1].argmax())
        ids[0, n] = nxt
        n += 1
    dt = time.perf_counter() - t0
    print("generated ids:", ids[0, prompt.shape[1]:n].tolist())
    print(f"{(n - prompt.shape[1]) / dt:.2f} tok/s")


if __name__ == "__main__":
    main()
