"""examples/onnx/gpt2 — GPT-2 through the sonnx path + greedy generation
(BASELINE.json:9: "GPT-2 ... inference via sonnx import").

Like bert.py: imports `--onnx <path>` if given, else exports our zoo
GPT-2 and reimports it, asserting logits parity.  Generation uses the
zoo model's KV-cached `generate()` (singa_tpu/models/_generate.py): one
compiled prefill + one compiled decode step whose per-token cost is
independent of how many tokens have been generated.  With `--onnx`
(imported graph only, no native weights) generation falls back to
re-running the fixed-length imported graph per token.

    python examples/onnx/gpt2.py --steps 8
    python examples/onnx/gpt2.py --onnx gpt2.onnx --device tpu
"""

import argparse
import time

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np  # noqa: E402

# importing common pins the cpu backend when --device cpu was passed
import common  # noqa: E402,F401

import singa_tpu as singa
from singa_tpu import models, sonnx
from singa_tpu.tensor import Tensor


def main():
    p = argparse.ArgumentParser(description="GPT-2 via sonnx + generation")
    p.add_argument("--onnx", default="", help="path to a GPT-2 .onnx file")
    p.add_argument("--device", default="auto", choices=["auto", "cpu", "tpu"])
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--dim", type=int, default=128)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--vocab", type=int, default=1000)
    p.add_argument("--seq", type=int, default=32)
    p.add_argument("--steps", type=int, default=8, help="tokens to generate")
    args = p.parse_args()

    dev = singa.device.create_device(args.device)
    singa.device.set_default_device(dev)

    rng = np.random.RandomState(0)
    prompt = rng.randint(0, args.vocab, (1, args.seq // 2)).astype(np.int64)

    if args.onnx:
        model_proto = sonnx.load(args.onnx)
        ref = None
    else:
        cfg = models.GPT2Config(vocab_size=args.vocab, dim=args.dim,
                                num_heads=args.heads, num_layers=args.layers,
                                max_position=max(64, args.seq), dropout=0.0)
        native = models.GPT2(cfg)
        full = np.zeros((1, args.seq), np.int64)
        full[0, :prompt.shape[1]] = prompt
        t_full = Tensor(data=full, device=dev)
        ref = np.asarray(native(t_full).data)
        print("exporting GPT-2 to ONNX via sonnx.to_onnx ...")
        model_proto = sonnx.to_onnx(native, [t_full])
        print(f"  graph: {len(model_proto.graph.node)} nodes")

    rep = sonnx.prepare(model_proto, device=dev)

    ids = np.zeros((1, args.seq), np.int64)
    n = prompt.shape[1]
    ids[0, :n] = prompt
    t_ids = Tensor(data=ids, device=dev)
    if ref is not None:
        (logits,) = rep.run([t_ids])
        err = np.max(np.abs(np.asarray(logits.data) - ref))
        print(f"import vs native max |diff| = {err:.2e}")
        assert err < 1e-2

    steps = min(args.steps, args.seq - n)
    if ref is not None:
        # native zoo weights available: KV-cached generate() — compiled
        # prefill + single compiled decode step reused for every token
        print(f"greedy generation (KV cache), {steps} tokens:")
        out = native.generate(prompt.astype(np.int32), steps)  # warm compile
        t0 = time.perf_counter()
        out = native.generate(prompt.astype(np.int32), steps)
        dt = time.perf_counter() - t0
        gen = out[0, prompt.shape[1]:].tolist()
        # cross-check the first tokens against the imported-graph loop
        check = ids.copy()
        cn = n
        for _ in range(min(2, steps)):
            t_ids.copy_from(check)
            (logits,) = rep.run([t_ids])
            check[0, cn] = int(np.asarray(logits.data)[0, cn - 1].argmax())
            cn += 1
        assert gen[:cn - n] == check[0, n:cn].tolist(), \
            "KV-cached generation diverged from the sonnx-imported graph"
        print("generated ids:", gen)
        print(f"{steps / dt:.2f} tok/s (decode cost independent of length)")
    else:
        # imported graph only: fixed-length re-run per token
        print(f"greedy generation (imported graph), {steps} tokens:")
        t0 = time.perf_counter()
        for _ in range(steps):
            t_ids.copy_from(ids)
            (logits,) = rep.run([t_ids])
            ids[0, n] = int(np.asarray(logits.data)[0, n - 1].argmax())
            n += 1
        dt = time.perf_counter() - t0
        print("generated ids:", ids[0, prompt.shape[1]:n].tolist())
        print(f"{(n - prompt.shape[1]) / dt:.2f} tok/s")


if __name__ == "__main__":
    main()
