"""Shared helpers for the example scripts (reference examples/ layout,
BASELINE.json:7-11).

Datasets: each loader first looks for a local .npz (this image has no
network egress, so no downloads); otherwise it falls back to a
deterministic synthetic set with the same shapes, which keeps every
script runnable end-to-end anywhere."""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))


def _pin_cpu_backend_if_requested():
    """`--device cpu` must take effect before any JAX backend initializes
    (the TPU plugin tunnel can take tens of seconds to come up)."""
    if "--device" in sys.argv:
        i = sys.argv.index("--device")
        if i + 1 < len(sys.argv) and sys.argv[i + 1] == "cpu":
            import jax
            jax.config.update("jax_platforms", "cpu")


_pin_cpu_backend_if_requested()

import singa_tpu as singa  # noqa: E402
from singa_tpu.utils.data import DataLoader, synthetic_dataset


def dataset_arrays(name: str, data_dir: str = "", n_synth: int = 2048):
    """Return (x_train, y_train, x_test, y_test, num_classes, input_shape).

    Real data: `<data_dir>/<name>.npz` with arrays x_train/y_train/
    x_test/y_test (images in NHWC float32 [0,1] or uint8)."""
    shapes = {
        "mnist": ((28, 28, 1), 10),
        "cifar10": ((32, 32, 3), 10),
        "cifar100": ((32, 32, 3), 100),
        "imagenet": ((224, 224, 3), 1000),
    }
    if name not in shapes:
        raise ValueError(f"unknown dataset {name}; options: {sorted(shapes)}")
    shape, classes = shapes[name]
    path = os.path.join(data_dir or ".", f"{name}.npz")
    if data_dir and os.path.exists(path):
        z = np.load(path)
        xt = z["x_train"].astype(np.float32)
        if xt.max() > 2.0:
            xt = xt / 255.0
        xe = z["x_test"].astype(np.float32)
        if xe.max() > 2.0:
            xe = xe / 255.0
        if xt.ndim == 3:
            xt, xe = xt[..., None], xe[..., None]
        return (xt, z["y_train"].astype(np.int32),
                xe, z["y_test"].astype(np.int32), classes, shape)
    n_test = max(256, n_synth // 8)
    x, y = synthetic_dataset("images", n=n_synth + n_test, classes=classes,
                             shape=shape)
    return (x[:n_synth], y[:n_synth], x[n_synth:], y[n_synth:], classes, shape)


def base_parser(desc: str) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=desc)
    p.add_argument("--device", default="auto",
                   choices=["auto", "cpu", "tpu"],
                   help="the reference's one-line device change "
                        "(BASELINE.json:5)")
    p.add_argument("--data-dir", default="", help="dir with <dataset>.npz")
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--graph", action=argparse.BooleanOptionalAction,
                   default=True, help="compiled graph mode vs eager")
    p.add_argument("--dist", action="store_true",
                   help="data-parallel over all local devices via DistOpt")
    p.add_argument("--bf16", action="store_true", help="bfloat16 inputs")
    p.add_argument("--log-every", type=int, default=20)
    return p


def make_device(kind: str):
    return singa.device.create_device(kind)


def train_classifier(model, args, x_train, y_train, x_test, y_test,
                     opt_factory=None):
    """The canonical reference training loop (examples/cnn/train.py
    shape): compile once, train_one_batch per step, eval per epoch."""
    from singa_tpu import opt as opt_mod
    from singa_tpu import parallel
    from singa_tpu.tensor import Tensor
    from singa_tpu.utils import metrics

    dev = make_device(args.device)
    singa.device.set_default_device(dev)
    base = (opt_factory() if opt_factory
            else opt_mod.SGD(lr=args.lr, momentum=0.9, weight_decay=1e-4))
    if args.dist:
        parallel.set_mesh(parallel.data_parallel_mesh())
        sgd = opt_mod.DistOpt(base)
    else:
        sgd = base
    model.set_optimizer(sgd)

    if args.bf16:
        import ml_dtypes
        dtype = ml_dtypes.bfloat16
    else:
        dtype = np.float32
    tx = Tensor(data=x_train[:args.batch_size].astype(dtype), device=dev)
    ty = Tensor(data=y_train[:args.batch_size].astype(np.int32), device=dev)
    model.compile([tx], is_train=True, use_graph=args.graph)

    loader = DataLoader(x_train, y_train, batch_size=args.batch_size,
                        drop_last=True)
    tput = metrics.Throughput()
    for epoch in range(args.epochs):
        model.train()
        acc = metrics.Accuracy()
        loss_m = metrics.MeanMeter()
        t0 = time.perf_counter()
        for step, (xb, yb) in enumerate(loader):
            tx.copy_from(xb.astype(dtype))
            ty.copy_from(yb.astype(np.int32))
            out, loss = model.train_one_batch(tx, ty)
            loss_m.update(float(np.asarray(loss.data)))
            acc.update(np.asarray(out.data), yb)
            tput.update(len(xb))
            if args.log_every and step % args.log_every == 0:
                print(f"epoch {epoch} step {step:4d} "
                      f"loss {loss_m.value:.4f} acc {acc.value:.4f}")
        dt = time.perf_counter() - t0
        test_acc = evaluate(model, x_test, y_test, args.batch_size, dev)
        print(f"epoch {epoch}: train loss {loss_m.value:.4f} "
              f"acc {acc.value:.4f}  test acc {test_acc:.4f}  "
              f"({len(x_train) / dt:.0f} imgs/s)")
    return model


def evaluate(model, x_test, y_test, batch_size, dev) -> float:
    from singa_tpu.tensor import Tensor
    from singa_tpu.utils import metrics

    model.eval()
    acc = metrics.Accuracy()
    tx = None
    for s in range(0, len(x_test) - batch_size + 1, batch_size):
        xb = x_test[s:s + batch_size].astype(np.float32)
        if tx is None:
            tx = Tensor(data=xb, device=dev)
        else:
            tx.copy_from(xb)
        out = model(tx)
        acc.update(np.asarray(out.data), y_test[s:s + batch_size])
    return acc.value
