"""examples/rnn — character-level LSTM language model (reference
lineage: the singa char-rnn example; SURVEY.md §2.2 row 7 RNN/LSTM).

Trains next-character prediction over a built-in corpus (no downloads:
this image has no network egress; pass --text for your own file), then
samples from the model.

    python examples/rnn/train.py --device cpu --steps 200
    python examples/rnn/train.py --device cpu --sample 200
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np  # noqa: E402

import common  # noqa: E402,F401  (pins the cpu backend for --device cpu)

from singa_tpu import layer, model, opt, tensor  # noqa: E402

_CORPUS = (
    "the quick brown fox jumps over the lazy dog. "
    "pack my box with five dozen liquor jugs. "
    "how vexingly quick daft zebras jump! "
    "sphinx of black quartz, judge my vow. "
    "the five boxing wizards jump quickly. "
    "a mad boxer shot a quick, gloved jab to the jaw of his "
    "dizzy opponent. jackdaws love my big sphinx of quartz. "
    "the jay, pig, fox, zebra and my wolves quack! "
    "few quips galvanized the mock jury box. "
    "crazy fredrick bought many very exquisite opal jewels. "
) * 8


class CharRNN(model.Model):
    """Embedding -> stacked LSTM -> per-step Linear over the vocab."""

    def __init__(self, vocab, hidden=128, embed=64, num_layers=2):
        super().__init__()
        self.vocab = vocab
        self.embed = layer.Embedding(vocab, embed)
        self.rnns = [layer.LSTM(hidden) for _ in range(num_layers)]
        self.head = layer.Linear(vocab)

    def forward(self, ids):
        x = self.embed(ids)                       # (B, T, E)
        for rnn in self.rnns:
            x = rnn(x)                            # (B, T, H)
        B, T, H = x.shape
        return self.head(x.reshape((B * T, H)))   # (B*T, V) logits


def batches(data, batch, seqlen, rng):
    starts = rng.randint(0, len(data) - seqlen - 1, size=batch)
    x = np.stack([data[s:s + seqlen] for s in starts])
    y = np.stack([data[s + 1:s + seqlen + 1] for s in starts])
    return x.astype(np.int32), y.reshape(-1).astype(np.int32)


def sample(m, text, stoi, itos, n, temperature=0.8, win=32):
    """Greedy-ish sampling with the training forward (teacher-forced
    window).  The seed is the corpus' first `win` chars, so the eval
    context is ALWAYS (1, win) and graph mode compiles exactly once."""
    rng = np.random.RandomState(0)
    ids = [stoi[c] for c in text[:win]]
    for _ in range(n):
        ctx = np.asarray(ids[-win:], np.int32)[None, :]
        logits = m(tensor.from_numpy(ctx)).to_numpy()
        logits = logits.reshape(ctx.shape[1], -1)[-1] / max(temperature, 1e-3)
        p = np.exp(logits - logits.max())
        p /= p.sum()
        ids.append(int(rng.choice(len(p), p=p)))
    return "".join(itos[i] for i in ids)


def main():
    p = common.base_parser("char-level LSTM LM (reference char-rnn)")
    p.add_argument("--text", default=None, help="path to a text corpus")
    p.add_argument("--steps", type=int, default=150)
    p.add_argument("--hidden", type=int, default=128)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--seqlen", type=int, default=64)
    p.add_argument("--sample", type=int, default=120,
                   help="characters to sample after training")
    p.set_defaults(lr=3e-3)      # char-LM-appropriate Adam step size
    args = p.parse_args()

    text = (open(args.text).read() if args.text else _CORPUS)
    chars = sorted(set(text))
    stoi = {c: i for i, c in enumerate(chars)}
    itos = {i: c for c, i in stoi.items()}
    data = np.asarray([stoi[c] for c in text], np.int32)
    print(f"corpus: {len(text)} chars, vocab {len(chars)}")

    tensor.set_seed(0)
    rng = np.random.RandomState(0)
    m = CharRNN(len(chars), hidden=args.hidden, num_layers=args.layers)
    m.set_optimizer(opt.Adam(lr=args.lr))
    x0, y0 = batches(data, args.batch_size, args.seqlen, rng)
    tx = tensor.from_numpy(x0)
    m.compile([tx], is_train=True, use_graph=args.graph)

    import time
    t0 = time.perf_counter()
    for step in range(args.steps):
        x, y = batches(data, args.batch_size, args.seqlen, rng)
        _, loss = m.train_step(tensor.from_numpy(x), tensor.from_numpy(y))
        if step % max(1, args.steps // 10) == 0 or step == args.steps - 1:
            lv = float(loss.to_numpy())
            dt = time.perf_counter() - t0
            cps = args.batch_size * args.seqlen * (step + 1) / dt
            print(f"step {step:4d}: loss {lv:.4f}  {cps:,.0f} chars/s")

    if args.sample:
        m.eval()
        print("--- sample ---")
        print(sample(m, text, stoi, itos, args.sample))


if __name__ == "__main__":
    main()
