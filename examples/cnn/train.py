"""examples/cnn — the reference CNN workloads (BASELINE.json:8,10:
MNIST CNN, CIFAR ResNet-18/VGG in singa.model graph mode, ImageNet
ResNet-50 data-parallel).

    python examples/cnn/train.py --model cnn      --dataset mnist
    python examples/cnn/train.py --model resnet18 --dataset cifar10
    python examples/cnn/train.py --model vgg11    --dataset cifar10
    python examples/cnn/train.py --model resnet50 --dataset imagenet --dist
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

from common import base_parser, dataset_arrays, train_classifier  # noqa: E402

from singa_tpu import models  # noqa: E402

_MODELS = {
    "mlp": lambda c: models.MLP(num_classes=c),
    "cnn": lambda c: models.CNN(num_classes=c),
    "lenet": lambda c: models.LeNet5(num_classes=c),
    "alexnet": lambda c: models.AlexNet(num_classes=c),
    "resnet18": lambda c: models.resnet18(num_classes=c),
    "resnet34": lambda c: models.resnet34(num_classes=c),
    "resnet50": lambda c: models.resnet50(num_classes=c),
    "vgg11": lambda c: models.vgg11(num_classes=c),
    "vgg13": lambda c: models.vgg13(num_classes=c),
    "vgg16": lambda c: models.vgg16(num_classes=c),
}


def main():
    p = base_parser("CNN family on MNIST/CIFAR/ImageNet (reference examples/cnn)")
    p.add_argument("--model", default="cnn", choices=sorted(_MODELS))
    p.add_argument("--dataset", default="mnist",
                   choices=["mnist", "cifar10", "cifar100", "imagenet"])
    args = p.parse_args()
    xt, yt, xe, ye, classes, _ = dataset_arrays(args.dataset, args.data_dir)
    m = _MODELS[args.model](classes)
    train_classifier(m, args, xt, yt, xe, ye)


if __name__ == "__main__":
    main()
