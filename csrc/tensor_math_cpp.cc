/* tensor_math_cpp — eager CPU kernels for the CppCPU debug device.
 * Parity target: the reference's per-device math dispatch table
 * (BASELINE.json:5 "tensor_math_cuda" analogue for host).  Blocked GEMM
 * with OpenMP; everything float32 row-major contiguous. */

#include "singa_core.h"

#include <algorithm>
#include <cmath>
#include <cstring>
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

namespace {
constexpr int64_t kBlockM = 64;
constexpr int64_t kBlockN = 64;
constexpr int64_t kBlockK = 64;

inline const float* row(const float* p, int64_t i, int64_t stride) {
  return p + i * stride;
}
}  // namespace

extern "C" {

void sg_gemm(const float* a, const float* b, float* c,
             int64_t m, int64_t k, int64_t n,
             int transa, int transb, float alpha, float beta) {
  // C[m,n] = alpha * op(A)[m,k] @ op(B)[k,n] + beta * C
  // Blocked ikj loop; packs nothing (fine for a debug device).
#pragma omp parallel for schedule(static)
  for (int64_t i0 = 0; i0 < m; i0 += kBlockM) {
    int64_t i1 = std::min(i0 + kBlockM, m);
    std::vector<float> acc(kBlockM * n);
    std::fill(acc.begin(), acc.end(), 0.f);
    for (int64_t k0 = 0; k0 < k; k0 += kBlockK) {
      int64_t k1 = std::min(k0 + kBlockK, k);
      for (int64_t i = i0; i < i1; ++i) {
        float* acc_i = acc.data() + (i - i0) * n;
        for (int64_t kk = k0; kk < k1; ++kk) {
          float av = transa ? a[kk * m + i] : a[i * k + kk];
          if (av == 0.f) continue;
          const float* brow = transb ? nullptr : b + kk * n;
          if (!transb) {
            for (int64_t j = 0; j < n; ++j) acc_i[j] += av * brow[j];
          } else {
            for (int64_t j = 0; j < n; ++j) acc_i[j] += av * b[j * k + kk];
          }
        }
      }
    }
    for (int64_t i = i0; i < i1; ++i) {
      float* ci = c + i * n;
      const float* acc_i = acc.data() + (i - i0) * n;
      if (beta == 0.f) {
        for (int64_t j = 0; j < n; ++j) ci[j] = alpha * acc_i[j];
      } else {
        for (int64_t j = 0; j < n; ++j) ci[j] = alpha * acc_i[j] + beta * ci[j];
      }
    }
  }
}

#define SG_EW(name, expr)                                          \
  void name(const float* a, const float* b, float* out, int64_t n) { \
    _Pragma("omp parallel for schedule(static)")                   \
    for (int64_t i = 0; i < n; ++i) out[i] = (expr);               \
  }

SG_EW(sg_add, a[i] + b[i])
SG_EW(sg_sub, a[i] - b[i])
SG_EW(sg_mul, a[i] * b[i])
SG_EW(sg_div, a[i] / b[i])
#undef SG_EW

void sg_axpy(float alpha, const float* x, float* y, int64_t n) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) y[i] += alpha * x[i];
}

void sg_scale(float alpha, float* x, int64_t n) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) x[i] *= alpha;
}

void sg_relu(const float* a, float* out, int64_t n) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) out[i] = a[i] > 0.f ? a[i] : 0.f;
}

void sg_relu_grad(const float* a, const float* dy, float* out, int64_t n) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) out[i] = a[i] > 0.f ? dy[i] : 0.f;
}

void sg_sigmoid(const float* a, float* out, int64_t n) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) out[i] = 1.f / (1.f + std::exp(-a[i]));
}

void sg_tanh(const float* a, float* out, int64_t n) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) out[i] = std::tanh(a[i]);
}

void sg_exp(const float* a, float* out, int64_t n) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) out[i] = std::exp(a[i]);
}

void sg_softmax(const float* a, float* out, int64_t rows, int64_t cols) {
#pragma omp parallel for schedule(static)
  for (int64_t r = 0; r < rows; ++r) {
    const float* ar = row(a, r, cols);
    float* orow = out + r * cols;
    float mx = ar[0];
    for (int64_t j = 1; j < cols; ++j) mx = std::max(mx, ar[j]);
    float s = 0.f;
    for (int64_t j = 0; j < cols; ++j) {
      orow[j] = std::exp(ar[j] - mx);
      s += orow[j];
    }
    float inv = 1.f / s;
    for (int64_t j = 0; j < cols; ++j) orow[j] *= inv;
  }
}

void sg_sum(const float* a, float* out, int64_t n) {
  double s = 0.0;
#pragma omp parallel for reduction(+ : s) schedule(static)
  for (int64_t i = 0; i < n; ++i) s += a[i];
  out[0] = static_cast<float>(s);
}

void sg_conv2d_nhwc(const float* x, const float* w, float* y,
                    int64_t N, int64_t H, int64_t W, int64_t C,
                    int64_t KH, int64_t KW, int64_t OC,
                    int64_t sh, int64_t sw, int64_t ph, int64_t pw) {
  // im2col-free direct conv: adequate for the debug device's smoke runs.
  int64_t OH = (H + 2 * ph - KH) / sh + 1;
  int64_t OW = (W + 2 * pw - KW) / sw + 1;
#pragma omp parallel for collapse(2) schedule(static)
  for (int64_t n = 0; n < N; ++n) {
    for (int64_t oh = 0; oh < OH; ++oh) {
      for (int64_t ow = 0; ow < OW; ++ow) {
        float* yp = y + ((n * OH + oh) * OW + ow) * OC;
        for (int64_t oc = 0; oc < OC; ++oc) yp[oc] = 0.f;
        for (int64_t kh = 0; kh < KH; ++kh) {
          int64_t ih = oh * sh - ph + kh;
          if (ih < 0 || ih >= H) continue;
          for (int64_t kw = 0; kw < KW; ++kw) {
            int64_t iw = ow * sw - pw + kw;
            if (iw < 0 || iw >= W) continue;
            const float* xp = x + ((n * H + ih) * W + iw) * C;
            const float* wp = w + (kh * KW + kw) * C * OC;
            for (int64_t c = 0; c < C; ++c) {
              float xv = xp[c];
              const float* wrow = wp + c * OC;
              for (int64_t oc = 0; oc < OC; ++oc) yp[oc] += xv * wrow[oc];
            }
          }
        }
      }
    }
  }
}

void sg_sgd_update(float* param, const float* grad, float* mom,
                   float lr, float momentum, float weight_decay, int64_t n) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float g = grad[i] + weight_decay * param[i];
    if (mom != nullptr) {
      mom[i] = momentum * mom[i] + g;
      g = mom[i];
    }
    param[i] -= lr * g;
  }
}

const char* sg_version(void) { return "singa_core 0.1.0"; }

}  // extern "C"
