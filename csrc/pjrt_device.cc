/* pjrt_device — native TpuDevice touchpoint over the PJRT C API.
 *
 * SURVEY.md §7.1 stance: the TPU entry is PJRT.  The COMPUTE path
 * stays JAX/XLA in-process (building a second C++ client would contend
 * for the single tunneled chip — see docs/native_tpu_device.md), but
 * the device layer's native surface is real: this module dlopens a
 * PJRT plugin (libtpu.so or any other PJRT_Api provider), validates
 * the C-API version handshake, surfaces plugin attributes
 * (xla_version, stablehlo versions, ...), and — explicitly opt-in,
 * because client creation over a wedged tunnel can hang — creates a
 * client to enumerate devices and their descriptions.
 *
 * Compiled against the official pjrt_c_api.h shipped in this image
 * (tensorflow/include/xla/pjrt/c/pjrt_c_api.h).  Exposed as a plain C
 * API consumed via ctypes (no pybind11 in the image).
 */

#include <dlfcn.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#include "xla/pjrt/c/pjrt_c_api.h"

namespace {

struct Plugin {
  void* dl = nullptr;
  const PJRT_Api* api = nullptr;
  std::string init_error;  // empty if PJRT_Plugin_Initialize succeeded
  bool alive = false;
};

struct ClientHandle {
  PJRT_Client* client = nullptr;
  int64_t plugin = -1;
  bool alive = false;
};

std::mutex g_mu;
std::vector<Plugin> g_plugins;
std::vector<ClientHandle> g_clients;

void copy_str(const char* src, size_t n, char* dst, int64_t cap) {
  if (!dst || cap <= 0) return;
  size_t m = (n < static_cast<size_t>(cap) - 1) ? n : static_cast<size_t>(cap) - 1;
  if (src && m) std::memcpy(dst, src, m);
  dst[m] = '\0';
}

/* Collect an error's message and destroy it.  Returns true if err was
 * non-null (i.e. the call failed). */
bool take_error(const PJRT_Api* api, PJRT_Error* err, std::string* out) {
  if (!err) return false;
  PJRT_Error_Message_Args margs;
  std::memset(&margs, 0, sizeof(margs));
  margs.struct_size = PJRT_Error_Message_Args_STRUCT_SIZE;
  margs.error = err;
  api->PJRT_Error_Message(&margs);
  if (out) out->assign(margs.message, margs.message_size);
  PJRT_Error_Destroy_Args dargs;
  std::memset(&dargs, 0, sizeof(dargs));
  dargs.struct_size = PJRT_Error_Destroy_Args_STRUCT_SIZE;
  dargs.error = err;
  api->PJRT_Error_Destroy(&dargs);
  return true;
}

Plugin* get_plugin(int64_t h) {
  if (h < 0 || h >= static_cast<int64_t>(g_plugins.size())) return nullptr;
  Plugin* p = &g_plugins[h];
  return p->alive ? p : nullptr;
}

ClientHandle* get_client(int64_t c) {
  if (c < 0 || c >= static_cast<int64_t>(g_clients.size())) return nullptr;
  ClientHandle* ch = &g_clients[c];
  return ch->alive ? ch : nullptr;
}

}  // namespace

extern "C" {

/* Load a PJRT plugin shared object; resolve GetPjrtApi; optionally run
 * PJRT_Plugin_Initialize (init!=0).  Returns a handle >= 0, or -1 with
 * a message in err. */
int64_t sg_pjrt_load(const char* so_path, int init, char* err,
                     int64_t errcap) {
  std::lock_guard<std::mutex> lock(g_mu);
  Plugin p;
  p.dl = dlopen(so_path, RTLD_NOW | RTLD_LOCAL);
  if (!p.dl) {
    const char* m = dlerror();
    if (!m) m = "dlopen failed";
    copy_str(m, std::strlen(m), err, errcap);
    return -1;
  }
  using GetApiFn = const PJRT_Api* (*)();
  auto get_api = reinterpret_cast<GetApiFn>(dlsym(p.dl, "GetPjrtApi"));
  if (!get_api) {
    copy_str("no GetPjrtApi symbol", 20, err, errcap);
    dlclose(p.dl);
    return -1;
  }
  p.api = get_api();
  if (!p.api || p.api->struct_size == 0) {
    copy_str("GetPjrtApi returned null/empty", 30, err, errcap);
    dlclose(p.dl);
    return -1;
  }
  if (init && p.api->PJRT_Plugin_Initialize) {
    PJRT_Plugin_Initialize_Args args;
    std::memset(&args, 0, sizeof(args));
    args.struct_size = PJRT_Plugin_Initialize_Args_STRUCT_SIZE;
    take_error(p.api, p.api->PJRT_Plugin_Initialize(&args), &p.init_error);
  }
  p.alive = true;
  g_plugins.push_back(p);
  return static_cast<int64_t>(g_plugins.size()) - 1;
}

/* C-API version handshake: fills major/minor; returns the PJRT_Api
 * struct_size (>0), or -1 on a bad handle. */
int64_t sg_pjrt_api_version(int64_t h, int32_t* major, int32_t* minor) {
  std::lock_guard<std::mutex> lock(g_mu);
  Plugin* p = get_plugin(h);
  if (!p) return -1;
  if (major) *major = p->api->pjrt_api_version.major_version;
  if (minor) *minor = p->api->pjrt_api_version.minor_version;
  return static_cast<int64_t>(p->api->struct_size);
}

/* Message from PJRT_Plugin_Initialize, or "" if it succeeded. */
int sg_pjrt_init_error(int64_t h, char* buf, int64_t cap) {
  std::lock_guard<std::mutex> lock(g_mu);
  Plugin* p = get_plugin(h);
  if (!p) return -1;
  copy_str(p->init_error.c_str(), p->init_error.size(), buf, cap);
  return 0;
}

int64_t sg_pjrt_attr_count(int64_t h) {
  std::lock_guard<std::mutex> lock(g_mu);
  Plugin* p = get_plugin(h);
  if (!p || !p->api->PJRT_Plugin_Attributes) return -1;
  PJRT_Plugin_Attributes_Args args;
  std::memset(&args, 0, sizeof(args));
  args.struct_size = PJRT_Plugin_Attributes_Args_STRUCT_SIZE;
  if (take_error(p->api, p->api->PJRT_Plugin_Attributes(&args), nullptr))
    return -1;
  return static_cast<int64_t>(args.num_attributes);
}

/* Attribute i: name into `name`, value formatted as text into `val`.
 * Returns the PJRT_NamedValue_Type, or -1. */
int sg_pjrt_attr_get(int64_t h, int64_t i, char* name, int64_t ncap,
                     char* val, int64_t vcap) {
  std::lock_guard<std::mutex> lock(g_mu);
  Plugin* p = get_plugin(h);
  if (!p || !p->api->PJRT_Plugin_Attributes) return -1;
  PJRT_Plugin_Attributes_Args args;
  std::memset(&args, 0, sizeof(args));
  args.struct_size = PJRT_Plugin_Attributes_Args_STRUCT_SIZE;
  if (take_error(p->api, p->api->PJRT_Plugin_Attributes(&args), nullptr))
    return -1;
  if (i < 0 || i >= static_cast<int64_t>(args.num_attributes)) return -1;
  const PJRT_NamedValue& nv = args.attributes[i];
  copy_str(nv.name, nv.name_size, name, ncap);
  char tmp[256];
  switch (nv.type) {
    case PJRT_NamedValue_kString:
      copy_str(nv.string_value, nv.value_size, val, vcap);
      break;
    case PJRT_NamedValue_kInt64:
      std::snprintf(tmp, sizeof(tmp), "%lld",
                    static_cast<long long>(nv.int64_value));
      copy_str(tmp, std::strlen(tmp), val, vcap);
      break;
    case PJRT_NamedValue_kInt64List: {
      std::string s;
      for (size_t j = 0; j < nv.value_size; ++j) {
        std::snprintf(tmp, sizeof(tmp), "%s%lld", j ? "," : "",
                      static_cast<long long>(nv.int64_array_value[j]));
        s += tmp;
      }
      copy_str(s.c_str(), s.size(), val, vcap);
      break;
    }
    case PJRT_NamedValue_kFloat:
      std::snprintf(tmp, sizeof(tmp), "%g",
                    static_cast<double>(nv.float_value));
      copy_str(tmp, std::strlen(tmp), val, vcap);
      break;
    case PJRT_NamedValue_kBool:
      copy_str(nv.bool_value ? "true" : "false", nv.bool_value ? 4 : 5,
               val, vcap);
      break;
    default:
      copy_str("?", 1, val, vcap);
  }
  return static_cast<int>(nv.type);
}

/* ---- client surface: OPT-IN ONLY (can block indefinitely over a
 * wedged tunneled backend; callers must gate/timeout). ---- */

int64_t sg_pjrt_client_create(int64_t h, char* err, int64_t errcap) {
  const PJRT_Api* api = nullptr;
  {
    std::lock_guard<std::mutex> lock(g_mu);
    Plugin* p = get_plugin(h);
    if (!p || !p->api->PJRT_Client_Create) {
      copy_str("bad plugin handle", 17, err, errcap);
      return -1;
    }
    api = p->api;
  }
  // PJRT_Client_Create can block indefinitely over a wedged tunneled
  // backend: it must run OUTSIDE g_mu so the handshake-only calls
  // (api_version/attributes) stay responsive from other threads.
  PJRT_Client_Create_Args args;
  std::memset(&args, 0, sizeof(args));
  args.struct_size = PJRT_Client_Create_Args_STRUCT_SIZE;
  std::string msg;
  if (take_error(api, api->PJRT_Client_Create(&args), &msg)) {
    copy_str(msg.c_str(), msg.size(), err, errcap);
    return -1;
  }
  std::lock_guard<std::mutex> lock(g_mu);
  ClientHandle ch;
  ch.client = args.client;
  ch.plugin = h;
  ch.alive = true;
  g_clients.push_back(ch);
  return static_cast<int64_t>(g_clients.size()) - 1;
}

int64_t sg_pjrt_client_device_count(int64_t c) {
  std::lock_guard<std::mutex> lock(g_mu);
  ClientHandle* ch = get_client(c);
  if (!ch) return -1;
  Plugin* p = get_plugin(ch->plugin);
  if (!p) return -1;
  PJRT_Client_Devices_Args args;
  std::memset(&args, 0, sizeof(args));
  args.struct_size = PJRT_Client_Devices_Args_STRUCT_SIZE;
  args.client = ch->client;
  if (take_error(p->api, p->api->PJRT_Client_Devices(&args), nullptr))
    return -1;
  return static_cast<int64_t>(args.num_devices);
}

int sg_pjrt_client_platform(int64_t c, char* buf, int64_t cap) {
  std::lock_guard<std::mutex> lock(g_mu);
  ClientHandle* ch = get_client(c);
  if (!ch) return -1;
  Plugin* p = get_plugin(ch->plugin);
  if (!p) return -1;
  PJRT_Client_PlatformName_Args args;
  std::memset(&args, 0, sizeof(args));
  args.struct_size = PJRT_Client_PlatformName_Args_STRUCT_SIZE;
  args.client = ch->client;
  if (take_error(p->api, p->api->PJRT_Client_PlatformName(&args), nullptr))
    return -1;
  copy_str(args.platform_name, args.platform_name_size, buf, cap);
  return 0;
}

/* Debug description of device i (kind, coords, ...). */
int sg_pjrt_device_desc(int64_t c, int64_t i, char* buf, int64_t cap) {
  std::lock_guard<std::mutex> lock(g_mu);
  ClientHandle* ch = get_client(c);
  if (!ch) return -1;
  Plugin* p = get_plugin(ch->plugin);
  if (!p) return -1;
  PJRT_Client_Devices_Args dargs;
  std::memset(&dargs, 0, sizeof(dargs));
  dargs.struct_size = PJRT_Client_Devices_Args_STRUCT_SIZE;
  dargs.client = ch->client;
  if (take_error(p->api, p->api->PJRT_Client_Devices(&dargs), nullptr))
    return -1;
  if (i < 0 || i >= static_cast<int64_t>(dargs.num_devices)) return -1;
  PJRT_Device_GetDescription_Args gargs;
  std::memset(&gargs, 0, sizeof(gargs));
  gargs.struct_size = PJRT_Device_GetDescription_Args_STRUCT_SIZE;
  gargs.device = dargs.devices[i];
  if (take_error(p->api, p->api->PJRT_Device_GetDescription(&gargs), nullptr))
    return -1;
  PJRT_DeviceDescription_DebugString_Args sargs;
  std::memset(&sargs, 0, sizeof(sargs));
  sargs.struct_size = PJRT_DeviceDescription_DebugString_Args_STRUCT_SIZE;
  sargs.device_description = gargs.device_description;
  if (take_error(p->api,
                 p->api->PJRT_DeviceDescription_DebugString(&sargs), nullptr))
    return -1;
  copy_str(sargs.debug_string, sargs.debug_string_size, buf, cap);
  return 0;
}

void sg_pjrt_client_destroy(int64_t c) {
  std::lock_guard<std::mutex> lock(g_mu);
  ClientHandle* ch = get_client(c);
  if (!ch) return;
  Plugin* p = get_plugin(ch->plugin);
  if (p && p->api->PJRT_Client_Destroy) {
    PJRT_Client_Destroy_Args args;
    std::memset(&args, 0, sizeof(args));
    args.struct_size = PJRT_Client_Destroy_Args_STRUCT_SIZE;
    args.client = ch->client;
    take_error(p->api, p->api->PJRT_Client_Destroy(&args), nullptr);
  }
  ch->alive = false;
}

/* Note: the PJRT_Api and its attribute storage have process lifetime;
 * we keep the dl handle open (dlclose of a live PJRT plugin is unsafe)
 * and only mark the slot dead. */
void sg_pjrt_unload(int64_t h) {
  std::lock_guard<std::mutex> lock(g_mu);
  Plugin* p = get_plugin(h);
  if (p) p->alive = false;
}

}  // extern "C"
