/* allocator — size-bucketed host staging pool (parity: the reference
 * core's per-device memory pool; here it backs host-side staging for the
 * data pipeline and CppCPU replay buffers).  Freed blocks are cached by
 * size bucket and reused; sg_pool_trim() returns them to the OS. */

#include "singa_core.h"

#include <cstdlib>
#include <map>
#include <mutex>
#include <unordered_map>
#include <vector>

namespace {

std::mutex g_mu;
std::multimap<size_t, void*> g_free;            // size -> block
std::unordered_map<void*, size_t> g_size_of;    // live + cached blocks
size_t g_in_use = 0;
size_t g_reserved = 0;

size_t round_up(size_t b) {
  // 64B alignment buckets; power-of-two above 4KB to bound fragmentation
  if (b <= 4096) return (b + 63) & ~size_t(63);
  size_t p = 4096;
  while (p < b) p <<= 1;
  return p;
}

}  // namespace

extern "C" {

void* sg_pool_alloc(size_t bytes) {
  size_t sz = round_up(bytes ? bytes : 1);
  std::lock_guard<std::mutex> lock(g_mu);
  auto it = g_free.lower_bound(sz);
  if (it != g_free.end() && it->first == sz) {
    void* p = it->second;
    g_free.erase(it);
    g_in_use += sz;
    return p;
  }
  void* p = nullptr;
  if (posix_memalign(&p, 64, sz) != 0) return nullptr;
  g_size_of[p] = sz;
  g_in_use += sz;
  g_reserved += sz;
  return p;
}

void sg_pool_free(void* p) {
  if (!p) return;
  std::lock_guard<std::mutex> lock(g_mu);
  auto it = g_size_of.find(p);
  if (it == g_size_of.end()) {
    std::free(p);  // not ours; be permissive
    return;
  }
  g_in_use -= it->second;
  g_free.insert({it->second, p});
}

size_t sg_pool_bytes_in_use(void) {
  std::lock_guard<std::mutex> lock(g_mu);
  return g_in_use;
}

size_t sg_pool_bytes_reserved(void) {
  std::lock_guard<std::mutex> lock(g_mu);
  return g_reserved;
}

void sg_pool_trim(void) {
  std::lock_guard<std::mutex> lock(g_mu);
  for (auto& kv : g_free) {
    g_reserved -= kv.first;
    g_size_of.erase(kv.second);
    std::free(kv.second);
  }
  g_free.clear();
}

}  // extern "C"
