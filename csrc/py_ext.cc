/* py_ext — CPython C-API binding for the native core (SURVEY.md §2.2
 * row 5: the reference generates its Python binding from the C++ core;
 * pybind11 is not in this image, so this is a hand-written extension
 * using the CPython API + buffer protocol for zero-copy argument
 * passing).  The ctypes binding in singa_tpu/_core stays as the
 * fallback; _core routes the hot wrappers through this module when it
 * imports.
 *
 * All functions take contiguous f32 buffers (numpy arrays) and write
 * into caller-allocated outputs — no copies, no allocation on the hot
 * path. */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include "singa_core.h"

namespace {

struct Buf {
  Py_buffer view{};
  bool ok = false;
  ~Buf() {
    if (ok) PyBuffer_Release(&view);
  }
};

bool get_f32(PyObject* obj, Buf* b, bool writable, Py_ssize_t* n_out) {
  int flags = PyBUF_C_CONTIGUOUS | PyBUF_FORMAT
              | (writable ? PyBUF_WRITABLE : 0);
  if (PyObject_GetBuffer(obj, &b->view, flags) != 0) return false;
  b->ok = true;
  if (b->view.itemsize != 4
      || (b->view.format && b->view.format[0] != 'f')) {
    PyErr_SetString(PyExc_TypeError, "expected a contiguous float32 buffer");
    return false;
  }
  if (n_out) *n_out = b->view.len / 4;
  return true;
}

PyObject* py_version(PyObject*, PyObject*) {
  return PyUnicode_FromString(sg_version());
}

PyObject* py_gemm(PyObject*, PyObject* args) {
  PyObject *ao, *bo, *co;
  long long m, k, n;
  int ta, tb;
  if (!PyArg_ParseTuple(args, "OOOLLLpp", &ao, &bo, &co, &m, &k, &n,
                        &ta, &tb))
    return nullptr;
  Buf a, b, c;
  Py_ssize_t na = 0, nb = 0, nc = 0;
  if (!get_f32(ao, &a, false, &na) || !get_f32(bo, &b, false, &nb)
      || !get_f32(co, &c, true, &nc))
    return nullptr;
  if (na < m * k || nb < k * n || nc < m * n) {
    PyErr_SetString(PyExc_ValueError, "gemm buffer sizes inconsistent "
                                      "with (m, k, n)");
    return nullptr;
  }
  Py_BEGIN_ALLOW_THREADS
  sg_gemm(static_cast<const float*>(a.view.buf),
          static_cast<const float*>(b.view.buf),
          static_cast<float*>(c.view.buf), m, k, n, ta, tb, 1.0f, 0.0f);
  Py_END_ALLOW_THREADS
  Py_RETURN_NONE;
}

/* (a, b, out) elementwise */
template <void (*FN)(const float*, const float*, float*, int64_t)>
PyObject* py_binary(PyObject*, PyObject* args) {
  PyObject *ao, *bo, *oo;
  if (!PyArg_ParseTuple(args, "OOO", &ao, &bo, &oo)) return nullptr;
  Buf a, b, o;
  Py_ssize_t n = 0, nb = 0, no = 0;
  if (!get_f32(ao, &a, false, &n) || !get_f32(bo, &b, false, &nb)
      || !get_f32(oo, &o, true, &no))
    return nullptr;
  if (nb != n || no != n) {
    PyErr_SetString(PyExc_ValueError, "size mismatch");
    return nullptr;
  }
  Py_BEGIN_ALLOW_THREADS
  FN(static_cast<const float*>(a.view.buf),
     static_cast<const float*>(b.view.buf),
     static_cast<float*>(o.view.buf), n);
  Py_END_ALLOW_THREADS
  Py_RETURN_NONE;
}

/* (a, out) elementwise */
template <void (*FN)(const float*, float*, int64_t)>
PyObject* py_unary(PyObject*, PyObject* args) {
  PyObject *ao, *oo;
  if (!PyArg_ParseTuple(args, "OO", &ao, &oo)) return nullptr;
  Buf a, o;
  Py_ssize_t n = 0, no = 0;
  if (!get_f32(ao, &a, false, &n) || !get_f32(oo, &o, true, &no))
    return nullptr;
  if (no != n) {
    PyErr_SetString(PyExc_ValueError, "size mismatch");
    return nullptr;
  }
  Py_BEGIN_ALLOW_THREADS
  FN(static_cast<const float*>(a.view.buf),
     static_cast<float*>(o.view.buf), n);
  Py_END_ALLOW_THREADS
  Py_RETURN_NONE;
}

PyObject* py_softmax(PyObject*, PyObject* args) {
  PyObject *ao, *oo;
  long long rows, cols;
  if (!PyArg_ParseTuple(args, "OOLL", &ao, &oo, &rows, &cols))
    return nullptr;
  Buf a, o;
  Py_ssize_t n = 0, no = 0;
  if (!get_f32(ao, &a, false, &n) || !get_f32(oo, &o, true, &no))
    return nullptr;
  if (n != rows * cols || no != n) {
    PyErr_SetString(PyExc_ValueError, "size mismatch");
    return nullptr;
  }
  Py_BEGIN_ALLOW_THREADS
  sg_softmax(static_cast<const float*>(a.view.buf),
             static_cast<float*>(o.view.buf), rows, cols);
  Py_END_ALLOW_THREADS
  Py_RETURN_NONE;
}

PyObject* py_sgd_update(PyObject*, PyObject* args) {
  PyObject *po, *go, *mo;
  float lr, mom, wd;
  if (!PyArg_ParseTuple(args, "OOOfff", &po, &go, &mo, &lr, &mom, &wd))
    return nullptr;
  Buf p, g, m;
  Py_ssize_t n = 0, ng = 0;
  if (!get_f32(po, &p, true, &n) || !get_f32(go, &g, false, &ng))
    return nullptr;
  float* momp = nullptr;
  if (mo != Py_None) {
    Py_ssize_t nm = 0;
    if (!get_f32(mo, &m, true, &nm)) return nullptr;
    if (nm != n) {
      PyErr_SetString(PyExc_ValueError, "momentum size mismatch");
      return nullptr;
    }
    momp = static_cast<float*>(m.view.buf);
  }
  if (ng != n) {
    PyErr_SetString(PyExc_ValueError, "grad size mismatch");
    return nullptr;
  }
  Py_BEGIN_ALLOW_THREADS
  sg_sgd_update(static_cast<float*>(p.view.buf),
                static_cast<const float*>(g.view.buf), momp, lr, mom, wd, n);
  Py_END_ALLOW_THREADS
  Py_RETURN_NONE;
}

PyMethodDef kMethods[] = {
    {"version", py_version, METH_NOARGS, "native core version"},
    {"gemm", py_gemm, METH_VARARGS, "gemm(a, b, out, m, k, n, ta, tb)"},
    {"add", py_binary<sg_add>, METH_VARARGS, "add(a, b, out)"},
    {"sub", py_binary<sg_sub>, METH_VARARGS, "sub(a, b, out)"},
    {"mul", py_binary<sg_mul>, METH_VARARGS, "mul(a, b, out)"},
    {"div", py_binary<sg_div>, METH_VARARGS, "div(a, b, out)"},
    {"relu", py_unary<sg_relu>, METH_VARARGS, "relu(a, out)"},
    {"sigmoid", py_unary<sg_sigmoid>, METH_VARARGS, "sigmoid(a, out)"},
    {"tanh", py_unary<sg_tanh>, METH_VARARGS, "tanh(a, out)"},
    {"exp", py_unary<sg_exp>, METH_VARARGS, "exp(a, out)"},
    {"softmax", py_softmax, METH_VARARGS, "softmax(a, out, rows, cols)"},
    {"sgd_update", py_sgd_update, METH_VARARGS,
     "sgd_update(p, g, mom|None, lr, momentum, wd) in-place"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef kModule = {
    PyModuleDef_HEAD_INIT, "singa_core_ext",
    "CPython C-API binding over the singa native core (zero-copy buffers)",
    -1, kMethods,
};

}  // namespace

PyMODINIT_FUNC PyInit_singa_core_ext(void) {
  return PyModule_Create(&kModule);
}
