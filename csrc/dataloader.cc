/* dataloader — native threaded input pipeline (the reference core ships
 * a native data path; SURVEY.md §2.2 native checklist).  In-memory
 * dataset, background worker threads fill a bounded ring of shuffled
 * batches so host batch assembly overlaps device compute.
 *
 * Concurrency design (three condition variables, one mutex):
 *   cv_work  — workers wait for an epoch's work (cursor < total)
 *   cv_space — producers wait for ring space
 *   cv_ready — the consumer waits for a ready batch
 * Workers snapshot their permutation indices UNDER the lock, then copy
 * sample bytes outside it, so the consumer's epoch rewind (reshuffle +
 * cursor reset) never races batch assembly.  Epoch boundaries are
 * accounted on the CONSUMER side by batch count — robust to workers
 * pushing out of order. */

#include "singa_core.h"

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstring>
#include <mutex>
#include <numeric>
#include <random>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

struct Batch {
  std::vector<float> x;
  std::vector<int32_t> y;
  int64_t size = 0;
};

struct Loader {
  const float* x = nullptr;
  const int32_t* y = nullptr;
  int64_t n = 0, stride = 0, batch = 0;
  bool shuffle = false, drop_last = false;
  uint64_t seed = 0;

  // guarded by mu:
  std::vector<int64_t> perm;
  int64_t cursor = 0;
  int64_t epoch = 0;
  std::vector<Batch> ring;
  size_t head = 0, tail = 0, count = 0;
  int64_t consumed_this_epoch = 0;

  std::mutex mu;
  std::condition_variable cv_work, cv_space, cv_ready;
  std::vector<std::thread> workers;
  std::atomic<bool> stop{false};

  int64_t batches_per_epoch() const {
    return drop_last ? n / batch : (n + batch - 1) / batch;
  }

  int64_t samples_per_epoch() const { return batches_per_epoch() * batch; }

  void reshuffle_locked() {
    perm.resize(n);
    std::iota(perm.begin(), perm.end(), 0);
    if (shuffle) {
      std::mt19937_64 rng(seed + static_cast<uint64_t>(epoch));
      std::shuffle(perm.begin(), perm.end(), rng);
    }
  }

  void worker_loop() {
    std::vector<int64_t> idx;
    while (true) {
      // claim a batch's worth of indices under the lock
      {
        std::unique_lock<std::mutex> lock(mu);
        cv_work.wait(lock, [&] {
          return stop.load() || cursor < samples_per_epoch();
        });
        if (stop.load()) return;
        int64_t start = cursor;
        int64_t bsz = std::min(batch, n - start);
        cursor += batch;
        idx.resize(bsz);
        for (int64_t i = 0; i < bsz; ++i) idx[i] = perm[start + i];
      }
      // assemble outside the lock (perm snapshot taken; x is const)
      Batch b;
      b.size = static_cast<int64_t>(idx.size());
      b.x.resize(b.size * stride);
      b.y.resize(b.size);
      for (int64_t i = 0; i < b.size; ++i) {
        std::memcpy(b.x.data() + i * stride, x + idx[i] * stride,
                    stride * sizeof(float));
        b.y[i] = y ? y[idx[i]] : 0;
      }
      // publish
      {
        std::unique_lock<std::mutex> lock(mu);
        cv_space.wait(lock,
                      [&] { return stop.load() || count < ring.size(); });
        if (stop.load()) return;
        ring[tail] = std::move(b);
        tail = (tail + 1) % ring.size();
        ++count;
      }
      cv_ready.notify_one();
    }
  }
};

std::mutex g_mu;
std::unordered_map<int64_t, Loader*> g_loaders;
int64_t g_next = 1;

Loader* get(int64_t h) {
  std::lock_guard<std::mutex> lock(g_mu);
  auto it = g_loaders.find(h);
  return it == g_loaders.end() ? nullptr : it->second;
}

}  // namespace

extern "C" {

int64_t sg_loader_new(const float* x, const int32_t* y,
                      int64_t n, int64_t x_stride, int64_t batch,
                      int shuffle, uint64_t seed, int drop_last,
                      int workers, int prefetch) {
  if (!x || n <= 0 || batch <= 0 || x_stride <= 0) return -1;
  auto* ld = new Loader();
  ld->x = x;
  ld->y = y;
  ld->n = n;
  ld->stride = x_stride;
  ld->batch = batch;
  ld->shuffle = shuffle != 0;
  ld->drop_last = drop_last != 0;
  ld->seed = seed;
  if (ld->batches_per_epoch() <= 0) {
    delete ld;
    return -1;  // drop_last with batch > n yields no batches
  }
  {
    std::lock_guard<std::mutex> lock(ld->mu);
    ld->reshuffle_locked();
  }
  ld->ring.resize(std::max(2, prefetch));
  int nw = std::max(1, workers);
  for (int i = 0; i < nw; ++i)
    ld->workers.emplace_back([ld] { ld->worker_loop(); });
  std::lock_guard<std::mutex> lock(g_mu);
  int64_t id = g_next++;
  g_loaders[id] = ld;
  return id;
}

int64_t sg_loader_next(int64_t h, float* x_out, int32_t* y_out) {
  Loader* ld = get(h);
  if (!ld) return -1;
  Batch b;
  bool rewound = false;
  {
    std::unique_lock<std::mutex> lock(ld->mu);
    ld->cv_ready.wait(lock, [&] { return ld->count > 0 || ld->stop.load(); });
    if (ld->stop.load()) return -1;
    b = std::move(ld->ring[ld->head]);
    ld->head = (ld->head + 1) % ld->ring.size();
    --ld->count;
    if (++ld->consumed_this_epoch >= ld->batches_per_epoch()) {
      // consumer-side epoch boundary: all of this epoch's batches are
      // consumed, workers are parked (cursor exhausted) — safe to rewind
      ld->consumed_this_epoch = 0;
      ld->epoch++;
      ld->reshuffle_locked();
      ld->cursor = 0;
      rewound = true;
    }
  }
  ld->cv_space.notify_one();
  if (rewound) ld->cv_work.notify_all();
  std::memcpy(x_out, b.x.data(), b.size * ld->stride * sizeof(float));
  if (y_out) std::memcpy(y_out, b.y.data(), b.size * sizeof(int32_t));
  return b.size;
}

int64_t sg_loader_batches_per_epoch(int64_t h) {
  Loader* ld = get(h);
  return ld ? ld->batches_per_epoch() : -1;
}

void sg_loader_free(int64_t h) {
  Loader* ld = nullptr;
  {
    std::lock_guard<std::mutex> lock(g_mu);
    auto it = g_loaders.find(h);
    if (it == g_loaders.end()) return;
    ld = it->second;
    g_loaders.erase(it);
  }
  ld->stop.store(true);
  ld->cv_work.notify_all();
  ld->cv_space.notify_all();
  ld->cv_ready.notify_all();
  for (auto& t : ld->workers) t.join();
  delete ld;
}

}  // extern "C"
