/* singa_core — native runtime for singa_tpu.
 *
 * Capability parity with the reference's native core (SURVEY.md §2.2
 * rows 1-5; language evidence /root/reference/.gitignore:1-28 — C++
 * shared-library build artifacts):
 *   - tensor_math_cpp : eager CPU kernels for the CppCPU debug device
 *   - scheduler       : graph topo-sort + liveness memory planning
 *   - dataloader      : threaded shuffle/batch/prefetch pipeline
 *
 * The TPU compute path is XLA (that is the idiomatic native path to the
 * MXU); this library is the host-side runtime around it.  Exposed as a
 * plain C API consumed via ctypes (no pybind11 in the image).
 */
#ifndef SINGA_CORE_H_
#define SINGA_CORE_H_

#include <cstdint>
#include <cstddef>

extern "C" {

/* ---------------- tensor_math_cpp ---------------- */
/* All kernels: float32, contiguous row-major. */
void sg_gemm(const float* a, const float* b, float* c,
             int64_t m, int64_t k, int64_t n,
             int transa, int transb, float alpha, float beta);
void sg_add(const float* a, const float* b, float* out, int64_t n);
void sg_sub(const float* a, const float* b, float* out, int64_t n);
void sg_mul(const float* a, const float* b, float* out, int64_t n);
void sg_div(const float* a, const float* b, float* out, int64_t n);
void sg_axpy(float alpha, const float* x, float* y, int64_t n); /* y += a*x */
void sg_scale(float alpha, float* x, int64_t n);
void sg_relu(const float* a, float* out, int64_t n);
void sg_relu_grad(const float* a, const float* dy, float* out, int64_t n);
void sg_sigmoid(const float* a, float* out, int64_t n);
void sg_tanh(const float* a, float* out, int64_t n);
void sg_exp(const float* a, float* out, int64_t n);
void sg_softmax(const float* a, float* out, int64_t rows, int64_t cols);
void sg_sum(const float* a, float* out, int64_t n); /* out[0] = sum */
void sg_conv2d_nhwc(const float* x, const float* w, float* y,
                    int64_t N, int64_t H, int64_t W, int64_t C,
                    int64_t KH, int64_t KW, int64_t OC,
                    int64_t sh, int64_t sw, int64_t ph, int64_t pw);
void sg_sgd_update(float* param, const float* grad, float* mom,
                   float lr, float momentum, float weight_decay, int64_t n);

/* ---------------- pjrt_device ---------------- */
/* Native TpuDevice touchpoint: load a PJRT plugin (libtpu.so), do the
 * C-API version handshake, read plugin attributes; client creation is
 * opt-in (can hang over a wedged tunneled backend).  pjrt_device.cc. */
int64_t sg_pjrt_load(const char* so_path, int init, char* err,
                     int64_t errcap);
int64_t sg_pjrt_api_version(int64_t h, int32_t* major, int32_t* minor);
int     sg_pjrt_init_error(int64_t h, char* buf, int64_t cap);
int64_t sg_pjrt_attr_count(int64_t h);
int     sg_pjrt_attr_get(int64_t h, int64_t i, char* name, int64_t ncap,
                         char* val, int64_t vcap);
int64_t sg_pjrt_client_create(int64_t h, char* err, int64_t errcap);
int64_t sg_pjrt_client_device_count(int64_t c);
int     sg_pjrt_client_platform(int64_t c, char* buf, int64_t cap);
int     sg_pjrt_device_desc(int64_t c, int64_t i, char* buf, int64_t cap);
void    sg_pjrt_client_destroy(int64_t c);
void    sg_pjrt_unload(int64_t h);

/* ---------------- scheduler ---------------- */
/* Build a graph of ops; topo-sort; plan buffer reuse by liveness.
 * Handles are opaque int64 ids. */
int64_t sg_graph_new(void);
void    sg_graph_free(int64_t g);
/* add node: nin input buffer-ids, nout output buffer-ids (caller-chosen
 * dense ints), returns node id or -1 */
int64_t sg_graph_add_node(int64_t g, const char* name,
                          const int64_t* in_bufs, int64_t nin,
                          const int64_t* out_bufs, int64_t nout,
                          const int64_t* buf_sizes_out, int64_t flops);
/* topo order of node ids into out[n]; returns n or -1 on cycle */
int64_t sg_graph_toposort(int64_t g, int64_t* out, int64_t cap);
/* liveness-based memory plan: assigns each buffer an offset in a shared
 * arena (first-fit over free intervals). Returns arena bytes needed.
 * offsets[i] receives the offset of buffer id i (cap entries). */
int64_t sg_graph_plan_memory(int64_t g, int64_t* offsets, int64_t cap);
int64_t sg_graph_num_nodes(int64_t g);
int64_t sg_graph_total_flops(int64_t g);

/* ---------------- dataloader ---------------- */
/* In-memory dataset of (x, y) float32/int32 arrays; background threads
 * produce shuffled batches into a bounded ring buffer. */
int64_t sg_loader_new(const float* x, const int32_t* y,
                      int64_t n, int64_t x_stride /* floats per sample */,
                      int64_t batch, int shuffle, uint64_t seed,
                      int drop_last, int workers, int prefetch);
/* blocks until a batch is ready; writes batch data and returns the
 * actual batch size, 0 at epoch end (loader rewinds + reshuffles), or
 * -1 on error */
int64_t sg_loader_next(int64_t h, float* x_out, int32_t* y_out);
void    sg_loader_free(int64_t h);
int64_t sg_loader_batches_per_epoch(int64_t h);

/* ---------------- allocator (host staging pool) ---------------- */
void*  sg_pool_alloc(size_t bytes);
void   sg_pool_free(void* p);
size_t sg_pool_bytes_in_use(void);
size_t sg_pool_bytes_reserved(void);
void   sg_pool_trim(void);

const char* sg_version(void);

} /* extern "C" */

#endif /* SINGA_CORE_H_ */
