/* scheduler — native Graph/Scheduler (parity: BASELINE.json:5 "the
 * Graph/Scheduler that buffers singa.autograd ops"; reference lineage
 * keeps a Node/Edge graph, topo-sorts it and plans memory).
 *
 * In singa_tpu the *device-side* schedule belongs to XLA; this native
 * scheduler provides the host-side equivalents the reference core had:
 *   - Kahn topological ordering of the captured op graph (with a
 *     deterministic tie-break so replays are reproducible),
 *   - liveness analysis + first-fit arena planning for buffer reuse
 *     (reports how much memory a serial replay needs — used by the
 *     Python CapturedGraph introspection and the CppCPU replay path),
 *   - FLOP accounting for MFU reporting.
 */

#include "singa_core.h"

#include <algorithm>
#include <cstring>
#include <map>
#include <mutex>
#include <queue>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Node {
  std::string name;
  std::vector<int64_t> in_bufs;
  std::vector<int64_t> out_bufs;
  int64_t flops = 0;
};

struct Graph {
  std::vector<Node> nodes;
  std::unordered_map<int64_t, int64_t> buf_size;   // buffer id -> bytes
  std::unordered_map<int64_t, int64_t> producer;   // buffer id -> node id
};

std::mutex g_mu;
std::unordered_map<int64_t, Graph*> g_graphs;
int64_t g_next_id = 1;

Graph* get(int64_t h) {
  std::lock_guard<std::mutex> lock(g_mu);
  auto it = g_graphs.find(h);
  return it == g_graphs.end() ? nullptr : it->second;
}

}  // namespace

extern "C" {

int64_t sg_graph_new(void) {
  std::lock_guard<std::mutex> lock(g_mu);
  int64_t id = g_next_id++;
  g_graphs[id] = new Graph();
  return id;
}

void sg_graph_free(int64_t h) {
  std::lock_guard<std::mutex> lock(g_mu);
  auto it = g_graphs.find(h);
  if (it != g_graphs.end()) {
    delete it->second;
    g_graphs.erase(it);
  }
}

int64_t sg_graph_add_node(int64_t h, const char* name,
                          const int64_t* in_bufs, int64_t nin,
                          const int64_t* out_bufs, int64_t nout,
                          const int64_t* buf_sizes_out, int64_t flops) {
  Graph* g = get(h);
  if (!g) return -1;
  Node node;
  node.name = name ? name : "";
  node.in_bufs.assign(in_bufs, in_bufs + nin);
  node.out_bufs.assign(out_bufs, out_bufs + nout);
  node.flops = flops;
  int64_t id = static_cast<int64_t>(g->nodes.size());
  for (int64_t i = 0; i < nout; ++i) {
    g->buf_size[out_bufs[i]] = buf_sizes_out[i];
    g->producer[out_bufs[i]] = id;
  }
  g->nodes.push_back(std::move(node));
  return id;
}

int64_t sg_graph_toposort(int64_t h, int64_t* out, int64_t cap) {
  Graph* g = get(h);
  if (!g) return -1;
  int64_t n = static_cast<int64_t>(g->nodes.size());
  if (cap < n) return -1;
  std::vector<int64_t> indeg(n, 0);
  std::vector<std::vector<int64_t>> succ(n);
  for (int64_t i = 0; i < n; ++i) {
    for (int64_t b : g->nodes[i].in_bufs) {
      auto it = g->producer.find(b);
      if (it != g->producer.end() && it->second != i) {
        succ[it->second].push_back(i);
        indeg[i]++;
      }
    }
  }
  // min-heap on node id: deterministic order among ready nodes
  std::priority_queue<int64_t, std::vector<int64_t>, std::greater<int64_t>> ready;
  for (int64_t i = 0; i < n; ++i)
    if (indeg[i] == 0) ready.push(i);
  int64_t cnt = 0;
  while (!ready.empty()) {
    int64_t u = ready.top();
    ready.pop();
    out[cnt++] = u;
    for (int64_t v : succ[u])
      if (--indeg[v] == 0) ready.push(v);
  }
  return cnt == n ? n : -1;  // -1: cycle
}

int64_t sg_graph_plan_memory(int64_t h, int64_t* offsets, int64_t cap) {
  Graph* g = get(h);
  if (!g) return -1;
  int64_t n = static_cast<int64_t>(g->nodes.size());
  std::vector<int64_t> order(n);
  if (sg_graph_toposort(h, order.data(), n) != n) return -1;

  // liveness: buffer live from producing step to last consuming step
  std::unordered_map<int64_t, int64_t> born, dies;
  for (int64_t step = 0; step < n; ++step) {
    const Node& node = g->nodes[order[step]];
    for (int64_t b : node.out_bufs)
      if (!born.count(b)) born[b] = step;
    for (int64_t b : node.in_bufs) dies[b] = step;
  }
  for (auto& kv : born)
    if (!dies.count(kv.first)) dies[kv.first] = n;  // graph outputs live to end

  // events sorted by birth; first-fit into a free-interval list
  struct Interval {
    int64_t off, size;
  };
  std::vector<std::pair<int64_t, int64_t>> by_birth;  // (birth, buf)
  for (auto& kv : born) by_birth.push_back({kv.second, kv.first});
  std::sort(by_birth.begin(), by_birth.end());

  std::map<int64_t, int64_t> free_list;  // offset -> size
  int64_t arena = 0;
  std::vector<std::pair<int64_t, std::pair<int64_t, int64_t>>> active;  // (death, (off,size))
  std::unordered_map<int64_t, int64_t> assigned;

  // Free only buffers whose last read is STRICTLY before step t: an
  // output born at step t must not alias a buffer the same node reads.
  auto release_until = [&](int64_t t) {
    for (auto it = active.begin(); it != active.end();) {
      if (it->first < t) {
        int64_t off = it->second.first, sz = it->second.second;
        // coalesce into free list
        auto nxt = free_list.lower_bound(off);
        if (nxt != free_list.end() && off + sz == nxt->first) {
          sz += nxt->second;
          free_list.erase(nxt);
        }
        if (!free_list.empty()) {
          auto prv = free_list.lower_bound(off);
          if (prv != free_list.begin()) {
            --prv;
            if (prv->first + prv->second == off) {
              off = prv->first;
              sz += prv->second;
              free_list.erase(prv);
            }
          }
        }
        free_list[off] = sz;
        it = active.erase(it);
      } else {
        ++it;
      }
    }
  };

  for (auto& bb : by_birth) {
    int64_t t = bb.first, buf = bb.second;
    release_until(t);
    int64_t need = (g->buf_size.count(buf) ? g->buf_size[buf] : 0);
    need = (need + 63) & ~63;  // 64B alignment
    int64_t off = -1;
    for (auto it = free_list.begin(); it != free_list.end(); ++it) {
      if (it->second >= need) {
        off = it->first;
        int64_t rem = it->second - need;
        int64_t ro = it->first + need;
        free_list.erase(it);
        if (rem > 0) free_list[ro] = rem;
        break;
      }
    }
    if (off < 0) {
      off = arena;
      arena += need;
    }
    assigned[buf] = off;
    active.push_back({dies[buf], {off, need}});
  }

  if (offsets) {
    for (auto& kv : assigned)
      if (kv.first >= 0 && kv.first < cap) offsets[kv.first] = kv.second;
  }
  return arena;
}

int64_t sg_graph_num_nodes(int64_t h) {
  Graph* g = get(h);
  return g ? static_cast<int64_t>(g->nodes.size()) : -1;
}

int64_t sg_graph_total_flops(int64_t h) {
  Graph* g = get(h);
  if (!g) return -1;
  int64_t total = 0;
  for (auto& node : g->nodes) total += node.flops;
  return total;
}

}  // extern "C"
