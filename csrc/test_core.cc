/* test_core — native unit tests for libsinga_core, built under
 * ASan+UBSan by `make asan` (SURVEY.md §5 race-detection/sanitizer
 * plan: the C++ core gets a sanitizer build target exercised in CI;
 * tests/test_native.py runs this binary).  No gtest dependency — a
 * tiny CHECK macro keeps the image's toolchain sufficient. */

#include "singa_core.h"

#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

static int g_failures = 0;

#define CHECK(cond)                                                     \
  do {                                                                  \
    if (!(cond)) {                                                      \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__,      \
                   #cond);                                              \
      ++g_failures;                                                     \
    }                                                                   \
  } while (0)

#define CHECK_NEAR(a, b, tol) CHECK(std::fabs((a) - (b)) <= (tol))

static void test_elementwise() {
  const int64_t n = 1027;  // odd size: exercises any tail handling
  std::vector<float> a(n), b(n), out(n);
  for (int64_t i = 0; i < n; ++i) {
    a[i] = 0.01f * static_cast<float>(i - 500);
    b[i] = 1.0f + 0.001f * static_cast<float>(i);
  }
  sg_add(a.data(), b.data(), out.data(), n);
  CHECK_NEAR(out[17], a[17] + b[17], 1e-6f);
  sg_mul(a.data(), b.data(), out.data(), n);
  CHECK_NEAR(out[999], a[999] * b[999], 1e-6f);
  sg_relu(a.data(), out.data(), n);
  CHECK(out[0] == 0.0f && out[n - 1] > 0.0f);
  sg_sigmoid(a.data(), out.data(), n);
  CHECK_NEAR(out[500], 0.5f, 1e-6f);  // a[500] == 0
  float s = 0;
  std::vector<float> acc(1, 0.0f);
  sg_sum(a.data(), acc.data(), n);
  for (int64_t i = 0; i < n; ++i) s += a[i];
  CHECK_NEAR(acc[0], s, 1e-2f);
}

static void test_gemm() {
  const int64_t m = 7, k = 5, n2 = 3;
  std::vector<float> a(m * k), b(k * n2), c(m * n2, 0.0f);
  for (size_t i = 0; i < a.size(); ++i) a[i] = 0.1f * static_cast<float>(i % 11);
  for (size_t i = 0; i < b.size(); ++i) b[i] = 0.2f * static_cast<float>(i % 7);
  sg_gemm(a.data(), b.data(), c.data(), m, k, n2, 0, 0, 1.0f, 0.0f);
  // reference element
  float ref = 0;
  for (int64_t kk = 0; kk < k; ++kk) ref += a[2 * k + kk] * b[kk * n2 + 1];
  CHECK_NEAR(c[2 * n2 + 1], ref, 1e-5f);
}

static void test_scheduler() {
  int64_t g = sg_graph_new();
  // diamond: 0 -> {1, 2} -> 3 over buffers 0..3
  int64_t b0 = 0, b1 = 1, b2 = 2, b3 = 3;
  int64_t sz[1] = {256};
  int64_t in0[1] = {b0};
  int64_t out1[1] = {b1};
  sg_graph_add_node(g, "a", in0, 1, out1, 1, sz, 10);
  int64_t out2[1] = {b2};
  sg_graph_add_node(g, "b", out1, 1, out2, 1, sz, 10);
  int64_t out3[1] = {b3};
  sg_graph_add_node(g, "c", out1, 1, out3, 1, sz, 10);
  int64_t in3[2] = {b2, b3};
  sg_graph_add_node(g, "d", in3, 2, out1 /*reuse b1 name ok*/, 0, sz, 10);
  int64_t order[8];
  int64_t nn = sg_graph_toposort(g, order, 8);
  CHECK(nn == 4);
  CHECK(order[0] == 0);       // deterministic Kahn order
  CHECK(sg_graph_total_flops(g) == 40);
  int64_t offs[8];
  int64_t arena = sg_graph_plan_memory(g, offs, 8);
  CHECK(arena > 0 && arena <= 4 * 256);
  sg_graph_free(g);
}

static void test_pool() {
  size_t before = sg_pool_bytes_in_use();
  void* p = sg_pool_alloc(1000);
  CHECK(p != nullptr);
  std::memset(p, 0xAB, 1000);  // ASan validates the bounds
  CHECK(sg_pool_bytes_in_use() > before);
  sg_pool_free(p);
  void* q = sg_pool_alloc(1000);  // same size bucket, reused
  CHECK(q == p);
  sg_pool_free(q);
  sg_pool_trim();
}

static void test_loader() {
  const int64_t n = 37, stride = 4, batch = 8;
  std::vector<float> x(n * stride);
  std::vector<int32_t> y(n);
  for (int64_t i = 0; i < n; ++i) {
    y[i] = static_cast<int32_t>(i);
    for (int64_t j = 0; j < stride; ++j)
      x[i * stride + j] = static_cast<float>(i) + 0.1f * static_cast<float>(j);
  }
  int64_t h = sg_loader_new(x.data(), y.data(), n, stride, batch,
                            /*shuffle=*/1, /*seed=*/7, /*drop_last=*/0,
                            /*workers=*/2, /*prefetch=*/3);
  CHECK(h >= 0);
  CHECK(sg_loader_batches_per_epoch(h) == (n + batch - 1) / batch);
  std::vector<float> xb(batch * stride);
  std::vector<int32_t> yb(batch);
  // the loader rewinds+reshuffles at epoch end and never blocks the
  // consumer: read exactly two epochs' worth of batches
  const int64_t bpe = sg_loader_batches_per_epoch(h);
  for (int epoch = 0; epoch < 2; ++epoch) {
    int64_t seen = 0;
    std::vector<int> hit(n, 0);
    for (int64_t bi = 0; bi < bpe; ++bi) {
      int64_t got = sg_loader_next(h, xb.data(), yb.data());
      CHECK(got > 0);
      for (int64_t i = 0; i < got; ++i) {
        CHECK(yb[i] >= 0 && yb[i] < n);
        ++hit[yb[i]];
        CHECK_NEAR(xb[i * stride], static_cast<float>(yb[i]), 1e-6f);
      }
      seen += got;
    }
    CHECK(seen == n);
    for (int64_t i = 0; i < n; ++i) CHECK(hit[i] == 1);
  }
  sg_loader_free(h);
}

int main() {
  std::printf("singa_core native tests (%s)\n", sg_version());
  test_elementwise();
  test_gemm();
  test_scheduler();
  test_pool();
  test_loader();
  if (g_failures) {
    std::fprintf(stderr, "%d failure(s)\n", g_failures);
    return 1;
  }
  std::printf("ALL NATIVE TESTS PASSED\n");
  return 0;
}
