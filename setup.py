"""Build config for the native core (csrc/) as a CPython extension.

The framework is pure-Python-importable without it (the XLA path never
touches csrc), so the extension is best-effort: a missing toolchain
degrades to the pure build instead of failing the install — mirroring
singa_tpu._core's runtime fallback chain (C extension -> ctypes ->
XLA:CPU).
"""

from __future__ import annotations

import os

from setuptools import Extension, setup
from setuptools.command.build_ext import build_ext


class _BestEffortBuildExt(build_ext):
    def run(self):
        try:
            super().run()
        except Exception as e:  # pragma: no cover - toolchain-dependent
            print(f"WARNING: native core build failed ({e}); "
                  f"installing pure-Python (XLA-only) build")

    def build_extension(self, ext):
        try:
            super().build_extension(ext)
        except Exception as e:  # pragma: no cover
            print(f"WARNING: skipping {ext.name}: {e}")


_CSRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "csrc")
_HDR = os.path.join("csrc", "singa_core.h")
_CORE_SRCS = [os.path.join("csrc", f) for f in
              ("tensor_math_cpp.cc", "scheduler.cc", "dataloader.cc",
               "allocator.cc")]

setup(
    ext_modules=[
        # the ctypes-facing shared library (no Python API): scheduler,
        # loader, pool handles + the kernel table.  _core.lib() globs
        # libsinga_core*.so, so the cpython-suffixed name works.
        Extension(
            "singa_tpu._core.libsinga_core",
            sources=_CORE_SRCS,
            depends=[_HDR],
            include_dirs=[_CSRC],
            extra_compile_args=["-O3", "-std=c++17", "-fPIC", "-fopenmp"],
            extra_link_args=["-fopenmp", "-lpthread"],
            language="c++",
        ),
        # the CPython buffer-protocol binding for the hot kernels
        Extension(
            "singa_tpu._core.singa_core_ext",
            sources=[os.path.join("csrc", "py_ext.cc")] + _CORE_SRCS,
            depends=[_HDR],
            include_dirs=[_CSRC],
            extra_compile_args=["-O3", "-std=c++17", "-fPIC", "-fopenmp"],
            extra_link_args=["-fopenmp", "-lpthread"],
            language="c++",
        ),
    ],
    cmdclass={"build_ext": _BestEffortBuildExt},
)
