"""Benchmark suite against BASELINE.json's named metrics.

Headline (the ONE stdout JSON line the driver parses): Llama training
throughput + MFU on one chip through the compiled-graph path — forward +
backward + update in ONE XLA module with donated buffers.  MFU (and
vs_baseline) use the model's analytic FLOPs (6·N_matmul + attention
terms; the token-embedding gather is excluded — r5 corrected a ~19%
over-count by matching the formula against the compiled step's traced
jaxpr FLOPs, utils.flops).  XLA cost_analysis under-counts this graph
— it counts a lax.scan body once (the chunked fused CE) and sees no
FLOPs inside the Pallas flash kernel (proven on-chip by the
matmul_microbench session stage) — so it stays in the stderr detail
line as a diagnostic (BASELINE.json:2,5).  Timing: windowed
throughput, true-fenced (see _timed_steps).  History: r1-r3 vs_baseline
used cost-analysis MFU; r4 the 6N-with-embeddings analytic basis on
the 110M config; r5 the corrected basis on the 0.9B flagship.

Secondary metrics (BASELINE.json:2, emitted as `#`-prefixed stderr
lines after the headline so a driver timeout can never eat the JSON):
  * ResNet-50 images/sec/chip (examples/cnn workload)
  * BERT-base samples/sec through the sonnx import path
  * DistOpt allreduce achieved bandwidth (in-graph psum; on a 1-device
    host this runs on an 8-device virtual CPU mesh in a subprocess so
    the code path is still exercised and measured)

Never dies before printing the JSON line: the parent process runs the
suite in a subprocess with a hard timeout (the TPU plugin has been seen
both to *raise* at init — BENCH_r01 — and to *hang* indefinitely), and
falls back to a CPU subprocess, so a wedged backend can never eat the
stdout contract.

Usage: python bench.py                 # orchestrator; one stdout JSON line
       python bench.py --sub tpu|cpu   # internal: run the suite in-process
       python bench.py --allreduce-sub # internal subprocess mode
       python bench.py --quantized     # f32 vs int8_ring on the flagship
                                       # DP step (wire bytes + step time,
                                       # recorded to runs/records.jsonl)
"""

from __future__ import annotations

import json
import os
import sys
import time

_T0 = time.time()
_BUDGET_S = float(os.environ.get("SINGA_BENCH_BUDGET_S", "420"))


def _probe_flash(seqlen: int) -> None:
    """Compile-check the Pallas flash kernel on this backend; if Mosaic
    isn't supported here, fall back to the XLA-fused attention path
    rather than dying mid-benchmark."""
    import jax
    import jax.numpy as jnp

    try:
        from singa_tpu.ops.flash_attention import flash_attention
        q = jnp.zeros((1, min(512, seqlen), 2, 64), jnp.bfloat16)
        jax.block_until_ready(
            jax.jit(lambda q: flash_attention(q, q, q, causal=True))(q))
    except Exception as e:  # pragma: no cover - backend-specific
        print(f"# flash kernel unavailable ({type(e).__name__}); "
              f"using XLA attention", file=sys.stderr)
        os.environ["SINGA_DISABLE_FLASH"] = "1"


def _budget_left() -> float:
    return _BUDGET_S - (time.time() - _T0)


#: ResNet-50 TPU bench batch, shared with tools/tpu_session.py.
#: r4 swept batches up to 2048 — ON THE MANGLED NETWORK (the NCHW-feed
#: layout bug, fixed r5): the real layout-corrected ResNet-50 does
#: ~25x the compute and activation traffic per image, b1536 crashes
#: the tunnel's compile helper, and the old sweep numbers are void.
#: 256 is the classic per-accelerator ImageNet batch and fits v5e HBM
#: in bf16; the live secondary uses it for a faster bench run, while
#: tools/tpu_session.py tries 512 first for the record (b512 and b256
#: measured the same MFU, 0.273 vs 0.279 — r5) and walks down
#: (512 -> 256 -> 128 -> 64) until the compile helper accepts one.
RESNET50_TPU_BATCH = 256

#: per-step stats of the most recent _timed_steps call (ms):
#: {"min": .., "median": .., "mean": .., "max": .., "n": ..}
LAST_STEP_STATS: dict = {}


def _timed_steps(m, batch, steps: int, warmup: int):
    """Per-step time of the compiled train step.

    Primary number: WINDOWED throughput — windows of 8 back-to-back
    dispatches with one fence at each window end, median over windows
    (utils.timing.windowed_steps).  That is how a real training loop
    runs; r5 probe 3 (tools/dispatch_probe.py overhead) showed per-step fencing
    adds ~30 ms/step of host dispatch overhead on the tunneled chip that
    pipelined execution fully hides (fenced 186.8 ms vs 8-step windows
    156.4 ms vs 8 steps compiled into ONE lax.scan program 160.3 ms —
    windows agree with the single compiled program, so the windowed
    number is genuine device time, not a fencing artifact).  The median
    over >=4 windows absorbs the tunnel's 200x weather (one 45 s step
    amid 250 ms neighbours, r4).

    A short individually-fenced pass (the r1-r4 methodology) lands in
    LAST_STEP_STATS["fenced"] as the per-dispatch-latency diagnostic.
    Budget is respected inside the loops (BENCH_r02 lesson)."""
    from singa_tpu.utils.timing import fenced_steps, windowed_steps

    holder = {}

    def one():
        holder["out"] = m.train_step(*batch)
        return holder["out"][-1].data

    # honor the caller's `steps` total (the CPU fallback passes 3-5
    # and must stay cheap — ONE window of exactly `steps`, no fenced
    # pass); >=16 steps split into windows of 8 + the fenced diagnostic
    if steps >= 16:
        window_len = 8
        windows = max(2, min(8, steps // 8))
    else:
        window_len = max(1, steps)
        windows = 1
    dt, stats = windowed_steps(one, windows=windows, window_len=window_len,
                               warmup=warmup, budget_left=_budget_left)
    if steps >= 16 and _budget_left() > 45:
        _, fstats = fenced_steps(one, steps=8, warmup=0,
                                 budget_left=_budget_left)
        stats["fenced"] = fstats
    LAST_STEP_STATS.clear()
    LAST_STEP_STATS.update(stats)
    return dt, holder["out"]


def _detail(name: str, payload: dict) -> None:
    print("# " + json.dumps({"bench": name, **payload}), file=sys.stderr)


def _best_llama_batch(default: int = 8) -> int:
    """Batch for the TPU headline: env override, else the default.
    (The r4 committed-record b32 promotion is gone: the 0.9B flagship
    already fails the tunnel compile helper at b16 — see the record's
    llama_b16_scaling — so a record-driven bump could only crash the
    headline bench.)"""
    env = os.environ.get("SINGA_BENCH_LLAMA_BATCH")
    return int(env) if env else default


def bench_llama(dev, on_tpu: bool) -> dict:
    """Headline: flagship decoder, tokens/s + MFU (cost-analysis FLOPs)."""
    import numpy as np

    from singa_tpu import models, opt, tensor
    from singa_tpu.utils.metrics import peak_flops

    if on_tpu:
        # flagship: the 0.9B config sized for this chip (honest MFU
        # 0.65 vs 0.39 for the 110M `small` — r5 flagship sweep; the
        # `small` continuity row lives in tools/tpu_session.py).
        # steps=32 -> 4 windows x 8 back-to-back steps (+ the fenced
        # diagnostic pass): weather comes in multi-second bursts, so the
        # median over windows discards a congested patch
        cfg = models.LlamaConfig.base()
        batch, seqlen, steps, warmup = _best_llama_batch(8), 1024, 32, 2
    else:
        cfg = models.LlamaConfig.tiny()
        batch, seqlen, steps, warmup = 4, 64, 5, 1
        cfg.max_position = max(cfg.max_position, seqlen)
    # chunked fused lm-head+CE: the (B*T, V) logits are never
    # materialized or returned per step (~1 GB less HBM traffic/step on
    # the TPU config)
    cfg.fused_loss = True

    tensor.set_seed(0)
    np.random.seed(0)
    m = models.Llama(cfg)
    m.set_optimizer(opt.SGD(lr=0.01, momentum=0.9))
    ids = tensor.from_numpy(
        np.random.randint(0, cfg.vocab_size, (batch, seqlen)).astype(np.int32))
    m.compile([ids], is_train=True, use_graph=True)
    n_params = m.num_params()

    dt, out = _timed_steps(m, (ids,), steps, warmup)
    tok_per_s = batch * seqlen / dt
    peak = peak_flops(getattr(dev, "device_kind", None) or dev.platform)

    # Primary MFU from the model's analytic FLOPs (6N + attention
    # terms, PaLM-style — flops_per_token's docstring): XLA
    # cost_analysis UNDER-counts this graph — a lax.scan body (the
    # chunked fused CE, 32 iterations) is counted once, and the Pallas
    # flash kernel's FLOPs are opaque to it entirely (r4 measurement:
    # 7.55e12 counted vs 1.33e13 analytic at the bench shape).  The
    # cost-analysis number stays in the detail line as a diagnostic.
    flops_analytic = m.flops_per_token(seqlen) * batch * seqlen
    g = m.graph
    flops_ca = g.flops() if g is not None else 0.0
    mfu = flops_analytic / dt / peak
    loss = float(out[-1].to_numpy())
    _detail("llama_train", {
        "device": getattr(dev, "device_kind", "") or dev.platform,
        "params_m": round(n_params / 1e6, 1), "batch": batch, "seq": seqlen,
        "step_ms": round(dt * 1e3, 1), "tokens_per_s": round(tok_per_s, 1),
        "mfu_analytic": round(mfu, 4),
        "mfu_cost_analysis": round(flops_ca / dt / peak, 4) if flops_ca
        else None,
        "step_stats_ms": dict(LAST_STEP_STATS),
        "loss": round(loss, 4)})
    named = _named_models_vs_bar()
    if named:
        # the >=45% bar names ResNet-50 and BERT-base
        # (BASELINE.json:2,5).  Stderr-only: these are the COMMITTED
        # record's numbers (possibly another session), not this run's —
        # the live resnet50_train/bert_sonnx_train detail lines are the
        # measurements to compare against (ADVICE r4: the headline JSON
        # must carry only live results)
        _detail("named_models_vs_bar_committed", named)
    return {"metric": "llama_train_tokens_per_sec",
            "value": round(tok_per_s, 2), "unit": "tokens/s",
            "vs_baseline": round(mfu / 0.45, 4)}


def _named_models_vs_bar():
    """ResNet-50 / BERT analytic-MFU vs the 0.45 bar, from the
    committed tpu_session.json record (same chip, same methodology).
    The `source` key makes the provenance explicit: these are the
    committed record's numbers, not re-measured in this bench run —
    the live bench emits its own resnet50_train/bert_sonnx_train
    detail lines to compare against."""
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "tpu_session.json")) as f:
            st = json.load(f).get("stages", {})
        rn = ((st.get("resnet50") or {}).get("result") or {}).get("mfu")
        bt = ((st.get("bert_sonnx") or {}).get("result")
              or {}).get("mfu_analytic")
        out = {}
        if rn:
            out["resnet50"] = round(rn / 0.45, 4)
        if bt:
            out["bert_base"] = round(bt / 0.45, 4)
        if out:
            out["source"] = "tpu_session.json committed record"
        return out or None
    except Exception:  # noqa: BLE001 - informational field, never fatal
        return None


def bench_resnet50(dev, on_tpu: bool) -> None:
    """BASELINE.json:2: ResNet-50 training images/sec/chip."""
    import numpy as np

    from singa_tpu import models, opt, tensor
    from singa_tpu.utils.metrics import peak_flops

    tensor.set_seed(0)
    np.random.seed(0)
    if on_tpu:
        m = models.resnet50(num_classes=1000, cifar_stem=False)
        batch, hw, steps, warmup, name = (RESNET50_TPU_BATCH, 224, 32, 2,
                                          "resnet50")
    else:
        m = models.resnet18(num_classes=10, cifar_stem=True)
        batch, hw, steps, warmup, name = 4, 32, 3, 1, "resnet18-cifar(cpu)"
    m.set_optimizer(opt.SGD(lr=0.1, momentum=0.9, weight_decay=1e-4))
    # NHWC: the zoo's documented layout (models/cnn.py) — r1-r4 fed NCHW
    # here, which the NHWC convs silently mis-read as a 3-pixel-tall
    # image with `hw` channels; every earlier committed ResNet bench
    # number measured that mangled network (r5 flops_count audit)
    x = tensor.from_numpy(
        np.random.randn(batch, hw, hw, 3).astype(np.float32))
    y = tensor.from_numpy(
        np.random.randint(0, 10, (batch,)).astype(np.int32))
    m.compile([x], is_train=True, use_graph=True)
    dt, out = _timed_steps(m, (x, y), steps, warmup)
    g = m.graph
    peak = peak_flops(getattr(dev, "device_kind", None) or dev.platform)
    mfu_ca = (g.flops() / dt / peak) if (g is not None and g.flops()) \
        else 0.0
    # analytic MFU from the model's OWN traced conv/matmul FLOPs
    # (utils.flops walks the jaxpr: exact for this architecture; for
    # resnet50@224 it reproduces the published ~4.1 GFLOP/image).
    # Training ~= 3x forward (fwd + 2x in backward).
    from singa_tpu.utils.flops import model_forward_flops
    flops_step = 3 * model_forward_flops(m, x) * batch
    mfu = flops_step / dt / peak
    _detail("resnet50_train", {
        "model": name, "batch": batch, "image": hw,
        "step_ms": round(dt * 1e3, 1),
        "images_per_s": round(batch / dt, 1),
        "mfu_analytic": round(mfu, 4),
        "mfu_cost_analysis": round(mfu_ca, 4),
        # conv workload against the same 45% bar the Llama headline
        # reports (BASELINE.json:5) — convs can tell a different story
        # than matmuls (VERDICT r3 weak #4)
        "mfu_vs_45pct_bar": round(mfu / 0.45, 4),
        "step_stats_ms": dict(LAST_STEP_STATS),
        "loss": round(float(out[-1].to_numpy()), 4)})


def bench_bert_sonnx(dev, on_tpu: bool) -> None:
    """BASELINE.json:2: BERT-base samples/sec, through the sonnx import
    path (export native zoo BERT → reimport → compiled train step)."""
    import numpy as np

    from singa_tpu import autograd, models, opt, sonnx, tensor

    tensor.set_seed(0)
    np.random.seed(0)
    if on_tpu:
        # batch 256 amortizes the tunnel chip's per-op tax (see
        # bench_resnet50): 16 -> 256 measured 112 -> 1,136 samples/s
        cfg = models.BERTConfig(num_labels=2)
        batch, seq, steps, warmup = 256, 128, 32, 2
    else:
        cfg = models.BERTConfig.tiny(num_labels=2)
        batch, seq, steps, warmup = 2, 16, 3, 1
    native = models.BERT(cfg)
    ids = tensor.from_numpy(np.random.randint(
        0, cfg.vocab_size, (batch, seq)).astype(np.int32))
    proto = sonnx.to_onnx(native, [ids])
    rep = sonnx.prepare(proto)
    rep.set_optimizer(opt.SGD(lr=0.01, momentum=0.9))
    rep.set_loss(lambda outs, y: autograd.softmax_cross_entropy(
        outs[0] if isinstance(outs, (list, tuple)) else outs, y))
    labels = tensor.from_numpy(
        np.random.randint(0, 2, (batch,)).astype(np.int32))
    rep.compile([ids], is_train=True, use_graph=True)
    dt, out = _timed_steps(rep, (ids, labels), steps, warmup)
    # analytic MFU (BERT.flops_per_token: 6N + attention, embeddings
    # excluded): BERT-base is one of the two models the 45% bar names
    # (BASELINE.json:5)
    from singa_tpu.utils.metrics import peak_flops
    flops_step = native.flops_per_token(seq) * batch * seq
    peak = peak_flops(getattr(dev, "device_kind", None) or dev.platform)
    mfu = flops_step / dt / peak if on_tpu else None
    # sensitivity line (VERDICT r4 weak #6): the headline basis excludes
    # embedding tables (PaLM 6N convention); the inclusive basis answers
    # "does the bar still clear if you count them"
    n_embed = (cfg.vocab_size + cfg.max_position
               + cfg.type_vocab_size) * cfg.dim
    mfu_incl = ((flops_step + 6 * n_embed * batch * seq) / dt / peak
                if on_tpu else None)
    _detail("bert_sonnx_train", {
        "layers": cfg.num_layers, "dim": cfg.dim, "batch": batch, "seq": seq,
        "step_ms": round(dt * 1e3, 1),
        "samples_per_s": round(batch / dt, 1),
        "mfu_analytic": round(mfu, 4) if mfu else None,
        "mfu_analytic_with_embeddings": round(mfu_incl, 4) if mfu_incl
        else None,
        "mfu_vs_45pct_bar": round(mfu / 0.45, 4) if mfu else None,
        "step_stats_ms": dict(LAST_STEP_STATS),
        "loss": round(float(out[-1].to_numpy()), 4)})


def bench_llama_generate(dev, on_tpu: bool) -> None:
    """KV-cached decode throughput (prefill + N greedy decode steps,
    compile-once: one _GenSession reused across calls).  Decode perf
    regressions were invisible before this line (VERDICT r3 item 6)."""
    import numpy as np

    from singa_tpu import models, tensor

    tensor.set_seed(0)
    np.random.seed(0)
    if on_tpu:
        cfg = models.LlamaConfig.small()
        B, P, N = 8, 128, 128
    else:
        cfg = models.LlamaConfig.tiny()
        B, P, N = 2, 16, 8
    m = models.Llama(cfg)
    m.eval()
    prompt = np.random.randint(0, cfg.vocab_size, (B, P)).astype(np.int32)
    ids_t = tensor.from_numpy(prompt)
    m.compile([ids_t], is_train=False, use_graph=True)
    # decode is weight-read bound: bf16 params halve per-token HBM
    # traffic on TPU (CPU fallback stays f32 — bf16 is slow there)
    import jax.numpy as jnp
    pdt = jnp.bfloat16 if on_tpu else None
    t0 = time.perf_counter()
    m.generate(prompt, max_new_tokens=N,          # compiles prefill+decode
               param_dtype=pdt)
    t_first = time.perf_counter() - t0
    # median-of-3 (ADVICE r4: min-of-2 was the most flattering statistic
    # and inconsistent with the training benches); min kept alongside
    import statistics
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = m.generate(prompt, max_new_tokens=N,    # steady state
                         param_dtype=pdt)
        ts.append(time.perf_counter() - t0)
    dt = statistics.median(ts)
    assert out.shape == (B, P + N)
    assert len(m._gen_sessions) == 1, "decode re-compiled between calls"
    _detail("llama_generate", {
        "batch": B, "prompt": P, "new_tokens": N,
        "first_call_s": round(t_first, 2),
        "steady_s": round(dt, 3), "steady_s_min": round(min(ts), 3),
        "tokens_per_s": round(B * N / dt, 1),
        "ms_per_token": round(dt / N * 1e3, 2)})


def _serve_knobs(model, platform: str, defaults: dict) -> dict:
    """Table-resolved serve-arena knobs (ISSUE 14): explicit env
    overrides (``SINGA_BENCH_NUM_SLOTS`` / ``SINGA_BENCH_BLOCK_SIZE``,
    same style as ``SINGA_BENCH_LLAMA_BATCH``) win, then the committed
    best-config table's entry for this (model, platform), then the
    bench's own hand-carried ``defaults`` — announced loudly once by
    the table layer when no committed entry decides."""
    from singa_tpu.autotune import table as autotune_table

    explicit = {}
    for knob, env in (("num_slots", "SINGA_BENCH_NUM_SLOTS"),
                      ("block_size", "SINGA_BENCH_BLOCK_SIZE")):
        raw = os.environ.get(env)
        explicit[knob] = int(raw) if raw else None
    knobs = autotune_table.resolve(
        "serve", autotune_table.model_key(model), platform, explicit,
        defaults=defaults)
    return {"num_slots": int(knobs["num_slots"]),
            "block_size": int(knobs["block_size"])}


def bench_serve(dev, on_tpu: bool, record: bool = True,
                perf_attr: str | None = None) -> None:
    """serve_throughput: a mixed prompt-length request stream through
    the continuous-batching ServeEngine vs the same stream served as
    sequential GenerateMixin.generate calls (ISSUE 2 acceptance: >=1.5x
    tokens/s on the CPU workload, token-identical greedy outputs).

    Methodology — both sides serve ONE warmup request before their
    timed pass, then the identical stream end-to-end:

      * the engine's warmup compiles its only two programs, so its
        timed pass is fully warm no matter what lengths arrive;
      * the sequential path's warmup compiles one (1, P, S) session;
        every OTHER prompt length in the stream costs it a fresh
        session compile mid-stream, because `generate` is shape-
        specialized — exactly the re-prefill/recompile behavior that
        motivates the serving layer (a server cannot enumerate prompt
        shapes in advance).

    The headline speedup is that end-to-end ratio.  The detail line
    additionally reports `speedup_warm` — the same stream with every
    sequential session pre-compiled — which isolates the pure
    continuous-batching effect (one decode dispatch serves num_slots
    requests) from the shape-specialization effect; both are real
    serving costs, reported separately so neither hides the other.

    ISSUE 6 adds the paged-vs-fixed-arena comparison on the same
    stream: `paged_peak_concurrent` vs `fixed_max_concurrent` at EQUAL
    arena memory (same physical block budget, 4x the table rows — the
    fixed arena's ceiling is its slot count, paging's is live tokens),
    and shared- vs private-prefix TTFT p50 on a tenant system prompt
    (prefill runs only on the unshared suffix when the prefix is
    resident; `prefix_hit_tokens` counts the skipped work).

    Appends a validated `serve_throughput` entry to the obs run-record
    store (CPU runs as smoke entries, same rule as the training bench).

    ISSUE 16 adds runtime attribution: a per-program ledger
    (``obs.attr``) is installed around the two timed engine windows
    (plain + speculative), its snapshot is joined against the analytic
    cost model of the live engine's OWN lowered programs, and the
    result is dumped to ``perf_attr`` (a path) and/or appended as a
    ``perf_attr`` record — the trajectory ``tools.lint --perf`` gates.
    """
    import numpy as np

    from singa_tpu import models, tensor
    from singa_tpu.obs import attr as obs_attr
    from singa_tpu.serve import ServeEngine
    from singa_tpu.serve.metrics import ServeMetrics

    tensor.set_seed(0)
    np.random.seed(0)
    if on_tpu:
        cfg = models.LlamaConfig.small()
        num_slots, max_len, block_size, n_new = 12, 192, 32, 64
        plens, reps = (32, 64, 96, 128), 6
    else:
        # serve-bench config (models/llama.py serve_bench: shared with
        # the autotune serve sweep so the committed best-config entry
        # keys to the same architecture this bench resolves)
        cfg = models.LlamaConfig.serve_bench()
        num_slots, max_len, block_size, n_new = 12, 48, 8, 24
        # 24 requests over 12 slots: two full occupancy waves
        plens, reps = (6, 10, 12, 16), 6
    m = models.Llama(cfg)
    m.eval()
    # arena knobs resolve through the committed best-config table
    # (explicit env overrides win; the hardcoded pair above is the
    # loud-once fallback when no table entry covers this model)
    kn = _serve_knobs(m, "tpu" if on_tpu else "cpu",
                      {"num_slots": num_slots, "block_size": block_size})
    num_slots, block_size = kn["num_slots"], kn["block_size"]
    prompts = [np.random.randint(0, cfg.vocab_size, (p,)).astype(np.int32)
               for p in plens for _ in range(reps)]
    m.compile([tensor.from_numpy(prompts[0][None])], is_train=False,
              use_graph=False)

    # sequential: one warmup shape, then the timed end-to-end stream;
    # its outputs double as the token-identity reference
    m.generate(prompts[0][None], max_new_tokens=n_new)
    t0 = time.perf_counter()
    refs = [m.generate(p[None], max_new_tokens=n_new)[0, p.size:]
            for p in prompts]
    t_seq = time.perf_counter() - t0
    # diagnostic: the same stream fully warm (every session compiled)
    t0 = time.perf_counter()
    for p in prompts:
        m.generate(p[None], max_new_tokens=n_new)
    t_seq_warm = time.perf_counter() - t0

    # engine: one warmup request compiles its two programs, then the
    # timed stream through continuous batching
    eng = ServeEngine(m, num_slots, max_len, block_size=block_size)
    eng.submit(prompts[0], max_new_tokens=n_new)
    eng.run_until_idle()
    eng.metrics = ServeMetrics()
    # runtime-attribution ledger (ISSUE 16): covers exactly the two
    # timed windows below, so attributed_frac is meaningful against
    # window_s = t_eng + t_spec (warmup dispatches excluded)
    led = obs_attr.install()
    t0 = time.perf_counter()
    handles = [eng.submit(p, max_new_tokens=n_new) for p in prompts]
    eng.run_until_idle()
    t_eng = time.perf_counter() - t0
    obs_attr.uninstall()

    mismatched = sum(
        not np.array_equal(ref, np.asarray(h.tokens))
        for ref, h in zip(refs, handles))
    n_tok = sum(len(h.tokens) for h in handles)
    ttft = eng.metrics.snapshot()["ttft_ms"] or {}

    # ---- speculative decoding (ISSUE 13): spec-vs-plain on the SAME
    # stream.  Self-speculation ablation (draft == target): the accept
    # rate is 1.0 by construction, so the measurement isolates what
    # verify-k dispatch packing buys at THIS concurrency — at full
    # occupancy the draft costs as much as the target and the ratio
    # hovers near (k+1)/(2k+1); the committed loadgen spec-compare pair
    # measures the low-concurrency regime where speculation wins
    # end-to-end.  Streams are asserted token-identical either way.
    spec_k = 3
    # one extra block of arena headroom: submit() requires prompt +
    # budget + spec_k under max_len (the last verify window's writes)
    seng = ServeEngine(m, num_slots, max_len + block_size,
                       block_size=block_size, draft_model=m,
                       spec_k=spec_k)
    seng.submit(prompts[0], max_new_tokens=n_new)
    seng.run_until_idle()
    seng.metrics = ServeMetrics()
    obs_attr.install(led)       # same ledger: one attribution window
    t0 = time.perf_counter()
    spec_handles = [seng.submit(p, max_new_tokens=n_new)
                    for p in prompts]
    seng.run_until_idle()
    t_spec = time.perf_counter() - t0
    obs_attr.uninstall()
    mismatched += sum(
        not np.array_equal(ref, np.asarray(h.tokens))
        for ref, h in zip(refs, spec_handles))
    sm = seng.metrics.snapshot()

    # ---- paged-arena wins (ISSUE 6) -----------------------------------
    # (a) equal-memory concurrency: the same physical block budget a
    #     fixed (num_slots, max_len) arena burns, but 4x the table
    #     rows — paging admits as many requests as live TOKENS fit,
    #     so peak concurrency on the same stream beats the fixed
    #     arena's hard num_slots ceiling (requests only hold the
    #     blocks their current length needs).
    max_blocks = -(-max_len // block_size)
    pool_blocks = num_slots * max_blocks + 1
    wide = ServeEngine(m, 4 * num_slots, max_len,
                       block_size=block_size, num_blocks=pool_blocks,
                       max_queue=2 * len(prompts))
    wide.submit(prompts[0], max_new_tokens=n_new)
    wide.run_until_idle()
    wide_handles = [wide.submit(p, max_new_tokens=n_new)
                    for p in prompts]
    peak = 0
    while wide.pending:
        wide.step()
        peak = max(peak, wide.pool.active_count)
    mismatched += sum(
        not np.array_equal(ref, np.asarray(h.tokens))
        for ref, h in zip(refs, wide_handles))

    # (b) shared-prefix TTFT: one tenant system prompt, short private
    #     suffixes.  With the prefix resident, prefill runs only on
    #     the suffix chunks (visible in serve.prefix_hit_tokens); with
    #     sharing off, every request re-prefills the whole prompt.
    share_len = 2 * block_size
    sp = np.random.randint(0, cfg.vocab_size,
                           (share_len,)).astype(np.int32)
    sufs = [np.random.randint(0, cfg.vocab_size, (4,)).astype(np.int32)
            for _ in range(8)]
    shared_stats = {}
    for flag in (True, False):
        se = ServeEngine(m, num_slots, max_len, block_size=block_size,
                         share_prefix=flag)
        se.submit(np.concatenate([sp, sufs[0]]), max_new_tokens=4)
        se.run_until_idle()            # warm: prefix now resident
        se.metrics = ServeMetrics()
        for s in sufs[1:]:             # one at a time: pure TTFT, no
            se.submit(np.concatenate([sp, s]),  # queueing in the way
                      max_new_tokens=4)
            se.run_until_idle()
        st = se.metrics.snapshot()
        shared_stats[flag] = ((st["ttft_ms"] or {}).get("p50", 0.0),
                              st["prefix_hit_tokens"])

    payload = {
        "tokens_per_s": round(n_tok / t_eng, 1),
        "speedup_vs_sequential": round(t_seq / t_eng, 3),
        "ttft_p50_ms": round(ttft.get("p50", 0.0), 3),
        "ttft_p99_ms": round(ttft.get("p99", 0.0), 3),
        "requests": len(prompts),
        # paged-arena headline: concurrency at EQUAL arena memory
        # (fixed arena = num_slots ceiling) and prefix-cache TTFT
        "fixed_max_concurrent": num_slots,
        "paged_peak_concurrent": peak,
        "ttft_shared_prefix_p50_ms": round(shared_stats[True][0], 3),
        "ttft_private_prefix_p50_ms": round(shared_stats[False][0], 3),
        "prefix_hit_tokens": int(shared_stats[True][1]),
        # speculative decoding (ISSUE 13): the schema-linted pair
        # (both-or-neither) plus the spec side's wall-clock result at
        # this bench's full-occupancy regime
        "accept_rate": round(sm["accept_rate"] or 0.0, 4),
        "tokens_per_dispatch": round(sm["tokens_per_dispatch"] or 0.0,
                                     3),
        "spec_tokens_per_s": round(n_tok / t_spec, 1),
        "spec_speedup_vs_plain_engine": round(t_eng / t_spec, 3),
    }
    detail = dict(payload)
    detail.update({
        "spec_k": spec_k,
        "device": getattr(dev, "device_kind", "") or dev.platform,
        "num_slots": num_slots, "max_len": max_len,
        "block_size": block_size, "pool_blocks": pool_blocks,
        "prompt_lens": list(plens), "new_tokens": n_new,
        "sequential_tokens_per_s": round(n_tok / t_seq, 1),
        "sequential_warm_tokens_per_s": round(n_tok / t_seq_warm, 1),
        "speedup_warm": round(t_seq_warm / t_eng, 3),
        "greedy_mismatches": mismatched,
        "compiled_programs": list(eng.compiled_counts()),
        "engine_steps": eng.metrics.steps,
    })
    _detail("serve_throughput", detail)
    if mismatched:
        raise AssertionError(
            f"{mismatched}/{len(prompts)} engine outputs diverged from "
            f"GenerateMixin.generate greedy decode")
    if record:
        _record_serve(payload, "tpu" if on_tpu else "cpu",
                      getattr(dev, "device_kind", "") or dev.platform)
    _emit_perf_attr(led, seng, t_eng + t_spec, perf_attr,
                    record=record, on_tpu=on_tpu,
                    device_kind=getattr(dev, "device_kind", "")
                    or dev.platform)


def bench_arena_compare(dev, on_tpu: bool, record: bool = True) -> None:
    """`--serve --arena-compare` (ISSUE 17): peak measured concurrency
    at EQUAL arena memory, f32 paged arena vs int8 QuantKV arena.

    Methodology — PR 6's equal-memory harness with the byte budget as
    the controlled variable:

      * the budget is what a FIXED (num_slots, max_len) f32 arena
        burns (`fixed_max_concurrent` = that slot count — deliberately
        small so the paged side is BLOCK-bound, not request-bound;
        PR 6's own compare saturated its 24-request stream and could
        not see past the paging win);
      * the f32 paged engine gets exactly that block budget and a
        non-binding slot ceiling: its peak concurrency is what paging
        alone buys per byte (streams asserted token-identical to
        sequential generate);
      * the int8 engine gets as many QuantKV blocks as the SAME byte
        budget holds (`arena_bytes_int8 <= arena_bytes_f32`, both on
        the record) — ~3.5x the blocks at serve_bench shapes, so the
        same bytes admit >= 2x the peak concurrency;
      * int8 KV breaks bitwise greedy identity BY CONSTRUCTION, so the
        quality number on the record is the spec-verify referee's
        accept rate: the SAME int8 arena proposes as a draft against
        an f32 target referee (draft_kv_dtype="int8"), whose output
        streams ARE asserted token-identical — the committed
        accept_rate is the fraction of quantized proposals the
        full-precision referee kept.

    Appends ONE serve_throughput record carrying the arena five-tuple
    plus the referee pair (tokens_per_s/ttft on it are the int8
    engine's own timed pass)."""
    import numpy as np

    from singa_tpu import models, tensor
    from singa_tpu.serve import ServeEngine
    from singa_tpu.serve import mem as serve_mem

    tensor.set_seed(0)
    np.random.seed(0)
    if on_tpu:
        cfg = models.LlamaConfig.small()
        fixed_slots, max_len, block_size, n_new = 2, 192, 32, 64
        plens, reps = (32, 64, 96, 128), 8
    else:
        cfg = models.LlamaConfig.serve_bench()
        # a 2-slot fixed-arena byte budget against a 32-request stream:
        # small enough that BOTH paged sides stay block-bound (neither
        # peak touches the request count), so the ratio measures
        # concurrency per BYTE, not stream exhaustion
        fixed_slots, max_len, block_size, n_new = 2, 48, 8, 24
        plens, reps = (6, 10, 12, 16), 8
    m = models.Llama(cfg)
    m.eval()
    prompts = [np.random.randint(0, cfg.vocab_size, (p,)).astype(np.int32)
               for p in plens for _ in range(reps)]
    m.compile([tensor.from_numpy(prompts[0][None])], is_train=False,
              use_graph=False)
    m.generate(prompts[0][None], max_new_tokens=n_new)
    t0 = time.perf_counter()
    refs = [m.generate(p[None], max_new_tokens=n_new)[0, p.size:]
            for p in prompts]
    t_seq = time.perf_counter() - t0

    max_blocks = -(-max_len // block_size)
    pool_blocks = fixed_slots * max_blocks + 1

    def drive(eng):
        """Timed pass over the full stream; returns (handles, peak
        concurrency, wall seconds)."""
        eng.submit(prompts[0], max_new_tokens=n_new)
        eng.run_until_idle()
        from singa_tpu.serve.metrics import ServeMetrics
        eng.metrics = ServeMetrics()
        t0 = time.perf_counter()
        handles = [eng.submit(p, max_new_tokens=n_new) for p in prompts]
        peak = 0
        while eng.pending:
            eng.step()
            peak = max(peak, eng.pool.active_count)
        return handles, peak, time.perf_counter() - t0

    # f32 paged arena at the byte budget, slots non-binding
    wide = ServeEngine(m, len(prompts), max_len, block_size=block_size,
                       num_blocks=pool_blocks,
                       max_queue=2 * len(prompts))
    arena_f32 = serve_mem.arena_bytes(wide.pool.caches)
    handles, paged_peak, _ = drive(wide)
    mismatched = sum(not np.array_equal(ref, np.asarray(h.tokens))
                     for ref, h in zip(refs, handles))
    if mismatched:
        raise AssertionError(
            f"{mismatched}/{len(prompts)} f32 paged streams diverged "
            f"from GenerateMixin.generate greedy decode")

    # int8 arena: as many QuantKV blocks as the SAME bytes hold
    int8_bb = serve_mem.arena_block_bytes(
        serve_mem.quant_arena(m, 1, block_size))
    quant_blocks = arena_f32 // int8_bb
    quant = ServeEngine(m, len(prompts), max_len, block_size=block_size,
                        num_blocks=quant_blocks, kv_dtype="int8",
                        max_queue=2 * len(prompts))
    arena_int8 = serve_mem.arena_bytes(quant.pool.caches)
    assert arena_int8 <= arena_f32
    qhandles, quant_peak, t_quant = drive(quant)
    assert all(h.done and len(h.tokens) == n_new for h in qhandles)
    qsnap = quant.metrics.snapshot()
    qttft = qsnap["ttft_ms"] or {}
    n_tok = sum(len(h.tokens) for h in qhandles)

    # quality referee: the int8 arena proposes, the f32 target judges
    ref_eng = ServeEngine(m, fixed_slots, max_len + block_size,
                          block_size=block_size, draft_model=m,
                          spec_k=3, draft_kv_dtype="int8",
                          max_queue=2 * len(prompts))
    rhandles = [ref_eng.submit(p, max_new_tokens=n_new) for p in prompts]
    ref_eng.run_until_idle()
    mismatched = sum(not np.array_equal(ref, np.asarray(h.tokens))
                     for ref, h in zip(refs, rhandles))
    if mismatched:
        raise AssertionError(
            f"{mismatched}/{len(prompts)} referee streams diverged — "
            f"the f32 verify referee must keep greedy identity over "
            f"any draft, including a quantized one")
    rsnap = ref_eng.metrics.snapshot()

    payload = {
        "tokens_per_s": round(n_tok / t_quant, 1),
        "speedup_vs_sequential": round(t_seq / t_quant, 3),
        "ttft_p50_ms": round(qttft.get("p50", 0.0), 3),
        "ttft_p99_ms": round(qttft.get("p99", 0.0), 3),
        "requests": len(prompts),
        "fixed_max_concurrent": fixed_slots,
        "paged_peak_concurrent": paged_peak,
        "quant_peak_concurrent": quant_peak,
        "arena_bytes_f32": int(arena_f32),
        "arena_bytes_int8": int(arena_int8),
        "accept_rate": round(rsnap["accept_rate"] or 0.0, 4),
        "tokens_per_dispatch": round(rsnap["tokens_per_dispatch"]
                                     or 0.0, 3),
    }
    detail = dict(payload)
    detail.update({
        "device": getattr(dev, "device_kind", "") or dev.platform,
        "max_len": max_len, "block_size": block_size,
        "pool_blocks_f32": pool_blocks,
        "pool_blocks_int8": int(quant_blocks),
        "new_tokens": n_new,
        "concurrency_gain": round(quant_peak / max(paged_peak, 1), 3),
    })
    _detail("serve_arena_compare", detail)
    if quant_peak < 2 * paged_peak:
        raise AssertionError(
            f"int8 peak concurrency {quant_peak} is under 2x the f32 "
            f"paged peak {paged_peak} at equal arena memory "
            f"({arena_int8}/{arena_f32} B) — the int8 tier's "
            f"acceptance claim does not hold on this box")
    if record:
        _record_serve(payload, "tpu" if on_tpu else "cpu",
                      getattr(dev, "device_kind", "") or dev.platform)


def _emit_perf_attr(led, seng, window_s: float, dump_path: str | None,
                    *, record: bool, on_tpu: bool,
                    device_kind: str) -> None:
    """Join the serve bench's attribution ledger against the analytic
    cost model of the SPEC engine's own lowered programs (the superset:
    prefill_chunk/decode/verify at exactly the serving shapes), dump the
    payload to ``dump_path`` when given (the CI gate feeds it to
    ``tools.lint --perf``), and append a ``perf_attr`` record when
    ``record``.  Never fatal — attribution must not kill the bench."""
    try:
        from singa_tpu.obs import attr as obs_attr
        from tools.lint.perf import engine_features

        payload = obs_attr.attribution_payload(
            led.snapshot(), engine_features(seng), window_s)
        if dump_path:
            with open(dump_path, "w", encoding="utf-8") as f:
                json.dump(payload, f, indent=1, sort_keys=True)
            print(f"# perf_attr payload written to {dump_path}",
                  file=sys.stderr)
        if record:
            from singa_tpu.obs import record as obs_record
            entry = obs_record.new_entry(
                "perf_attr", "tpu" if on_tpu else "cpu", not on_tpu,
                device_kind, run_id=obs_record.new_run_id("perfattr"),
                payload=payload)
            store = os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                obs_record.DEFAULT_STORE)
            obs_record.RunRecord(store).append(entry)
            print(f"# perf_attr entry appended to {store} "
                  f"({len(payload['programs'])} programs, "
                  f"attributed {payload['attributed_frac']:.0%} of "
                  f"{window_s:.2f} s)", file=sys.stderr)
    except Exception as e:  # noqa: BLE001
        print(f"# perf_attr emission failed: {type(e).__name__}: {e}",
              file=sys.stderr)


def _record_serve(payload: dict, platform: str, device_kind: str) -> None:
    """Append the serving headline to the durable run-record store
    (kind=serve_throughput; tools/record_check.py lints it).  Never
    fatal — telemetry must not kill the bench."""
    try:
        from singa_tpu.obs import record as obs_record
        entry = obs_record.new_entry(
            "serve_throughput", platform, platform != "tpu", device_kind,
            run_id=obs_record.new_run_id("serve"), payload=payload)
        store = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             obs_record.DEFAULT_STORE)
        obs_record.RunRecord(store).append(entry)
        print(f"# serve_throughput entry appended to {store}",
              file=sys.stderr)
    except Exception as e:  # noqa: BLE001
        print(f"# serve store append failed: {type(e).__name__}: {e}",
              file=sys.stderr)


def _allreduce_bw(n: int, mib: float = 32.0, iters: int = 20) -> dict:
    """In-graph psum over an n-device 'data' mesh; returns achieved
    per-device algorithmic bandwidth (ring allreduce moves
    2(n-1)/n * bytes per device)."""
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from singa_tpu import parallel

    mesh = parallel.make_mesh({"data": n})
    nelem = int(mib * 2 ** 20 / 4)
    x = jnp.ones((n, nelem), jnp.float32)

    def timed(body):
        f = jax.jit(shard_map(body, mesh=mesh,
                              in_specs=P("data"), out_specs=P("data")))
        jax.block_until_ready(f(x))
        t0 = time.perf_counter()
        for _ in range(iters):
            out = f(x)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / iters

    dt = timed(lambda v: jax.lax.psum(v, "data"))
    bytes_payload = nelem * 4
    ring = 2.0 * (n - 1) / n
    return {"devices": n, "payload_mib": mib,
            "time_ms": round(dt * 1e3, 3),
            # algbw = payload/time; busbw applies the ring 2(n-1)/n factor
            # (NCCL-tests convention) for comparison with link peak
            "algbw_gb_s": round(bytes_payload / dt / 1e9, 2),
            "busbw_gb_s": round(ring * bytes_payload / dt / 1e9, 2),
            # bytes-on-wire per device per allreduce (ring model); the
            # quantized comparison lives in `bench.py --quantized` now
            "wire_bytes_f32": int(ring * bytes_payload),
            "platform": jax.devices()[0].platform}


def bench_allreduce() -> None:
    """BASELINE.json:2: DistOpt allreduce achieved bandwidth. With >1
    real devices measures ICI; on a 1-device host the same code path is
    measured on an 8-device virtual CPU mesh in a subprocess."""
    import subprocess

    import jax

    n = len(jax.devices())
    if n > 1:
        _detail("allreduce_bw", _allreduce_bw(n))
        return
    from singa_tpu.utils.virtcpu import with_device_count_flag

    env = dict(os.environ)
    env["XLA_FLAGS"] = with_device_count_flag(env.get("XLA_FLAGS", ""), 8)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--allreduce-sub"],
        env=env, capture_output=True, text=True, timeout=240,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    if r.returncode == 0 and r.stdout.strip():
        _detail("allreduce_bw", json.loads(r.stdout.strip().splitlines()[-1]))
    else:
        _detail("allreduce_bw", {"error": (r.stderr or "")[-300:]})


def _allreduce_sub_main() -> None:
    # the BENCH_r05 `quantized_sweep` payload sweep that used to ride
    # this subprocess was promoted to `python bench.py --quantized`
    # (the flagship DP step, static wire bytes + wall time, recorded);
    # this worker now measures only the f32 allreduce bandwidth
    from singa_tpu.utils.virtcpu import pin_virtual_cpu

    if not pin_virtual_cpu(8):
        raise SystemExit("could not pin an 8-device virtual CPU platform")
    print(json.dumps(_allreduce_bw(8, mib=8.0, iters=10)))


def _quantized_bench(steps: int = 20) -> dict:
    """f32 vs error-feedback int8_ring gradient sync on the flagship
    2-way-DP train step — the SAME tiny-Llama config the cost gate
    lowers as train_step_dp2 / train_step_dp2_int8, so the reported
    wire bytes are the COST005-gated numbers, not a parallel model.

    Per mode: compile through the real graph executor, time `steps`
    back-to-back steps, and compute per-participant collective wire
    bytes statically from the compiled HLO (tools.lint.cost ring
    model).  Replaces BENCH_r05's host-side `quantized_sweep` one-off;
    the win-regime discussion lives in docs/parallelism.md."""
    import jax
    import numpy as np

    from singa_tpu import models, opt, parallel, tensor
    from tools.lint import cost as lint_cost

    out: dict = {}
    for mode, compression in (("f32", None), ("int8_ring", "int8_ring")):
        tensor.set_seed(0)
        np.random.seed(0)
        parallel.set_mesh(parallel.make_mesh({"data": 2}))
        try:
            cfg = models.LlamaConfig.tiny()
            cfg.num_layers = 1
            m = models.Llama(cfg)
            m.set_optimizer(opt.DistOpt(opt.SGD(lr=0.01, momentum=0.9),
                                        compression=compression))
            ids = tensor.from_numpy(np.zeros((2, 16), np.int32))
            m.compile([ids], is_train=True, use_graph=True)
            m.train_step(ids)                       # compile + warm
            t0 = time.perf_counter()
            for _ in range(steps):
                res = m.train_step(ids)
            jax.block_until_ready(res[1].data)
            dt_ms = (time.perf_counter() - t0) / steps * 1e3
            wire = lint_cost.summarize_cost(
                m.graph.compiled_hlo(), f"train_step_dp2_{mode}")[
                    "wire_bytes"]
            out[mode] = {"step_ms": round(dt_ms, 3),
                         "wire_bytes": int(wire)}
        finally:
            parallel.set_mesh(None)
    f32_w, int8_w = out["f32"]["wire_bytes"], out["int8_ring"]["wire_bytes"]
    return {"metric": "int8_ring_wire_reduction",
            "value": round(f32_w / max(int8_w, 1), 3),
            "unit": "x_fewer_wire_bytes",
            "wire_bytes_f32_equiv": f32_w,
            "wire_bytes_compressed": int8_w,
            "f32_step_ms": out["f32"]["step_ms"],
            "int8_ring_step_ms": out["int8_ring"]["step_ms"],
            "steps": steps,
            "platform": "cpu"}


def _quantized_main() -> None:
    """`python bench.py --quantized`: the quantized-collectives bench
    on the 8-device virtual CPU platform (2-way DP mesh — the audited
    topology; CPU numbers gate bytes and relative time, not latency
    claims), appended to runs/records.jsonl as a linted bench record
    carrying the wire_bytes_compressed / wire_bytes_f32_equiv pair."""
    from singa_tpu.utils.virtcpu import pin_virtual_cpu

    if not pin_virtual_cpu(8):
        raise SystemExit("could not pin an 8-device virtual CPU platform")
    payload = _quantized_bench()
    _record_quantized(payload)
    print(json.dumps(payload), flush=True)


def _record_quantized(payload: dict) -> None:
    """Append the quantized bench outcome to the durable store (kind
    ``bench``; the schema lints the wire-byte pair).  Never fatal —
    the stdout contract outranks telemetry."""
    try:
        from singa_tpu.obs import record as obs_record
        entry = obs_record.new_entry(
            "bench", "cpu", True, "cpu",
            run_id=obs_record.new_run_id("quantized"),
            payload={"headline": payload,
                     "wire_bytes_compressed":
                         payload["wire_bytes_compressed"],
                     "wire_bytes_f32_equiv":
                         payload["wire_bytes_f32_equiv"]})
        store = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             obs_record.DEFAULT_STORE)
        obs_record.RunRecord(store).append(entry)
        print(f"# quantized bench entry appended to {store}",
              file=sys.stderr)
    except Exception as e:  # noqa: BLE001
        print(f"# quantized store append failed: {type(e).__name__}: {e}",
              file=sys.stderr)


def _enable_persistent_cache(platform: str) -> None:
    """Persist compiled executables across bench invocations (the repo
    dir survives between driver runs on this host).  First compile of
    the big train-step module over a tunneled backend is minutes; a
    cache hit is seconds.

    TPU-only: TPU executables are keyed by the TPU target, so entries
    primed on one host are valid on another.  XLA:CPU entries are
    AOT-compiled for the *priming host's* CPU features — loading them
    on a different machine risks SIGILL and floods stderr with
    feature-mismatch warnings (BENCH_r03: ~40 such lines drowned the
    headline JSON in the driver's tail capture)."""
    import jax

    if platform == "cpu":
        return
    cache_dir = os.environ.get(
        "SINGA_JAX_CACHE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"))
    if not cache_dir or cache_dir == "0":
        return
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception as e:  # pragma: no cover - version-dependent knobs
        print(f"# persistent cache unavailable: {type(e).__name__}",
              file=sys.stderr)


def _sub_main(platform: str) -> None:
    """Run the whole suite in-process on `platform` (called in a child)."""
    import jax

    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    _enable_persistent_cache(platform)
    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    if platform == "tpu" and not on_tpu:
        raise SystemExit("tpu requested but backend resolved to cpu")

    from singa_tpu import device, parallel

    parallel.set_mesh(None)
    if on_tpu:
        _probe_flash(1024)
        device.set_default_device(device.create_tpu_device())
    else:
        device.set_default_device(device.create_cpu_device())

    # Headline first: the stdout JSON line must survive any later crash
    # or timeout.  Secondaries cheapest-first (BENCH_r02: ResNet last —
    # its conv-heavy compile is the most likely budget-eater).
    headline = bench_llama(dev, on_tpu)
    print(json.dumps(headline), flush=True)
    try:
        _sub_main_secondaries(dev, on_tpu)
    finally:
        # BENCH_r03: the driver parses a bounded tail; anything noisy
        # after the headline can push it out.  Re-emit it as the child's
        # LAST stdout line no matter what the secondaries did.
        print(json.dumps(headline), flush=True)


def _sub_main_secondaries(dev, on_tpu: bool) -> None:

    # minimum seconds a bench realistically needs (compile + steps); skip
    # with an explicit line rather than getting killed mid-compile.  The
    # CPU fallback runs tiny configs — much smaller minima, so a CPU-only
    # round still emits all three secondary metrics (BENCH_r02/r03: the
    # TPU-sized minima made the CPU fallback skip BERT and ResNet)
    need = ({"bench_allreduce": 30, "bench_llama_generate": 80,
             "bench_serve": 140, "bench_bert_sonnx": 90,
             "bench_resnet50": 120} if on_tpu else
            {"bench_allreduce": 25, "bench_llama_generate": 30,
             "bench_serve": 60, "bench_bert_sonnx": 35,
             "bench_resnet50": 40})
    for fn, args in ((bench_allreduce, ()),
                     (bench_llama_generate, (dev, on_tpu)),
                     (bench_serve, (dev, on_tpu)),
                     (bench_bert_sonnx, (dev, on_tpu)),
                     (bench_resnet50, (dev, on_tpu))):
        if _budget_left() < need[fn.__name__]:
            print(f"# budget low ({_budget_left():.0f}s); "
                  f"skipping {fn.__name__}", file=sys.stderr)
            continue
        try:
            fn(*args)
        except Exception as e:
            print(f"# {fn.__name__} failed: {type(e).__name__}: {e}",
                  file=sys.stderr)


def _run_sub(platform: str, timeout_s: float) -> str | None:
    """Spawn `bench.py --sub <platform>` and STREAM its output: the
    child's headline JSON line is forwarded to our stdout the moment it
    appears (so a later hang in a secondary bench can't eat it); its
    stderr detail lines are forwarded to our stderr.  Returns the
    headline line once one was emitted, else None."""
    import subprocess
    import threading

    env = dict(os.environ)
    if platform == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
        # never load persistent-cache entries on the CPU fallback: they
        # may be AOT-compiled for another machine's CPU features
        # (SIGILL risk + stderr flood, BENCH_r03)
        env["SINGA_JAX_CACHE"] = "0"
    # soft budget below our hard timeout so the child can skip remaining
    # benches gracefully instead of being killed mid-bench
    env.setdefault("SINGA_BENCH_BUDGET_S", str(max(60, int(timeout_s) - 60)))
    p = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--sub", platform],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        bufsize=1, start_new_session=True,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    emitted = [None]

    def _pump_stdout():
        for line in p.stdout:
            line = line.strip()
            if not line:
                continue
            if line == emitted[0]:
                continue  # the child's end-of-run headline re-print
            if emitted[0] is None and line.startswith("{"):
                try:
                    if "metric" in json.loads(line):
                        print(line, flush=True)
                        emitted[0] = line
                        continue
                except json.JSONDecodeError:
                    pass
            print("# [sub stdout] " + line, file=sys.stderr)

    def _pump_stderr():
        for line in p.stderr:
            sys.stderr.write(line)
            sys.stderr.flush()

    ts = [threading.Thread(target=_pump_stdout, daemon=True),
          threading.Thread(target=_pump_stderr, daemon=True)]
    for t in ts:
        t.start()
    try:
        p.wait(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        # kill the whole process group: the child may have grandchildren
        # (e.g. the --allreduce-sub worker) that a bare kill() would orphan
        import signal
        try:
            os.killpg(p.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            p.kill()
        p.wait()
        print(f"# {platform} sub-bench timed out after {timeout_s:.0f}s "
              f"and was killed", file=sys.stderr)
    for t in ts:
        t.join(timeout=10)
    return emitted[0]


def _tpu_usable(timeout_s: float) -> str:
    """Probe in a subprocess: can the TPU backend init AND run a tiny
    jitted matmul within the timeout?  Protects against both failure
    modes seen under axon: a fast RuntimeError and an indefinite hang.

    Returns 'ok', 'hang' (worth retrying — wedged tunnels recover), or
    'fail' (deterministic: no TPU on this host)."""
    import subprocess

    code = ("import jax, jax.numpy as jnp;"
            "d = jax.devices();"
            "assert d[0].platform != 'cpu', d;"
            "x = jnp.ones((256, 256), jnp.bfloat16);"
            "jax.block_until_ready(jax.jit(lambda a: a @ a)(x));"
            "print('TPU_PROBE_OK', d[0].device_kind)")
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        print(f"# TPU probe hung >{timeout_s:.0f}s", file=sys.stderr)
        return "hang"
    if r.returncode == 0 and "TPU_PROBE_OK" in (r.stdout or ""):
        return "ok"
    tail = ((r.stderr or "").strip().splitlines() or [""])[-1]
    print(f"# TPU probe failed (rc={r.returncode}): {tail[:200]}",
          file=sys.stderr)
    return "fail"


def main() -> None:
    # Budgets: the recorded driver invocation ("python bench.py", no
    # wrapper timeout in BENCH_r01.json) sets no hard deadline, so
    # these bound our own worst case (~16 min: 3 hung probes 3x90s +
    # 2x45s backoff + wedged-after-probe TPU suite 420s + CPU suite
    # 180s).  A deterministic no-TPU host skips the retries and streams
    # the CPU headline at ~2min; a healthy TPU streams its headline
    # right after the llama bench.
    probe_timeout = float(os.environ.get("SINGA_BENCH_PROBE_TIMEOUT_S", "90"))
    # 900s: BENCH_r03 diagnosis — the big train-step compile over the
    # tunneled backend alone can eat most of the old 420s window even
    # with jit-init; the driver invocation has no wrapper deadline
    tpu_timeout = float(os.environ.get("SINGA_BENCH_TPU_TIMEOUT_S", "900"))
    cpu_timeout = float(os.environ.get("SINGA_BENCH_CPU_TIMEOUT_S", "300"))
    probe_tries = int(os.environ.get("SINGA_BENCH_PROBE_TRIES", "3"))

    # the axon tunnel has been observed to wedge for minutes-to-hours and
    # then recover — and killing a client mid-handshake can prolong the
    # wedge, so retries back off progressively (45s -> 2min -> 5min)
    # rather than hammering it; deterministic failures (no TPU on this
    # host) fall through to CPU immediately
    backoffs = [45, 120, 300]
    usable = False
    for attempt in range(probe_tries):
        status = _tpu_usable(probe_timeout)
        if status == "ok":
            usable = True
            break
        if status == "fail" or attempt + 1 >= probe_tries:
            break
        wait = backoffs[min(attempt, len(backoffs) - 1)]
        print(f"# TPU probe attempt {attempt + 1}/{probe_tries} hung; "
              f"retrying in {wait}s", file=sys.stderr)
        time.sleep(wait)

    headline = None
    platform = None
    if usable:
        headline = _run_sub("tpu", tpu_timeout)
        platform = "tpu" if headline is not None else None
    if headline is None:
        print("# no TPU headline; running the suite on CPU",
              file=sys.stderr)
        headline = _run_sub("cpu", cpu_timeout)
        platform = "cpu" if headline is not None else None
    if headline is None:
        headline = json.dumps({"metric": "llama_train_tokens_per_sec",
                               "value": 0.0, "unit": "tokens/s",
                               "vs_baseline": 0.0})
        platform = "none"
    _record_bench(headline, platform)
    _record_hlo_audit()
    # The driver parses a bounded tail of this process's output
    # (BENCH_r03: stderr noise after the early headline pushed it out of
    # the capture).  The LAST stdout line is always the headline JSON.
    print(headline, flush=True)


def _record_bench(headline: str, platform: str) -> None:
    """Append this bench run to the durable run-record store
    (singa_tpu.obs.record) so every headline has a committed,
    schema-validated artifact.  CPU fallbacks append as smoke entries —
    the store and its consumers never let them shadow on-chip runs.
    Never fatal: the stdout contract outranks telemetry."""
    try:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from singa_tpu.obs import record as obs_record
        entry = obs_record.new_entry(
            "bench", platform, platform != "tpu", platform,
            run_id=obs_record.new_run_id("bench"),
            payload={"headline": json.loads(headline)})
        store = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             obs_record.DEFAULT_STORE)
        obs_record.RunRecord(store).append(entry)
        print(f"# bench entry appended to {store}", file=sys.stderr)
    except Exception as e:  # noqa: BLE001
        print(f"# bench store append failed: {type(e).__name__}: {e}",
              file=sys.stderr)


def _record_hlo_audit() -> None:
    """Append the compiled-program audit summary (tools/lint/hlo.py
    structure + tools/lint/cost.py analytic cost — fusion/collective/
    donation structure AND flops/HBM/peak/wire numerics of the flagship
    train and serve programs, one shared lowering) to the run-record
    store next to the bench headline, so drift AND cost history
    accumulate with the perf trajectory: when a future headline moves,
    runs/records.jsonl can answer "did the compiled program change
    underneath it" and feed the record-driven autotuner's
    ``cost_features()`` inputs (ROADMAP item 4).

    Runs in a CPU subprocess — the gate pins the virtual-CPU backend
    itself, so this can never touch the axon tunnel no matter which
    platform the bench ran on.  Never fatal: the stdout contract
    outranks telemetry."""
    import subprocess
    try:
        from singa_tpu.utils.virtcpu import with_device_count_flag
        env = dict(os.environ)
        env["XLA_FLAGS"] = with_device_count_flag(
            env.get("XLA_FLAGS", ""), 8)
        env["JAX_PLATFORMS"] = "cpu"
        r = subprocess.run(
            [sys.executable, "-m", "tools.lint", "--hlo", "--json"],
            env=env, capture_output=True, text=True, timeout=180,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        doc = json.loads(r.stdout)       # emitted for exit 0 AND 1
        from singa_tpu.obs import record as obs_record
        entry = obs_record.new_entry(
            "hlo_audit", "cpu", True, "cpu",
            run_id=obs_record.new_run_id("hloaudit"),
            payload=doc["hlo"])
        store = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             obs_record.DEFAULT_STORE)
        obs_record.RunRecord(store).append(entry)
        print(f"# hlo_audit entry appended to {store} "
              f"(drifted={doc['hlo']['drifted']}, "
              f"flops={doc['hlo'].get('flops', 0):,}, "
              f"peak={doc['hlo'].get('peak_bytes', 0):,} B)",
              file=sys.stderr)
    except Exception as e:  # noqa: BLE001
        print(f"# hlo_audit record skipped: {type(e).__name__}: {e}",
              file=sys.stderr)


def _serve_only_main() -> None:
    """`python bench.py --serve`: run ONLY the serve_throughput bench on
    the current backend (CPU unless a TPU resolved) — the quick check of
    the ISSUE-2 acceptance numbers without the full orchestrator.
    `--no-record` skips the store append (the CI gate's table-resolved
    smoke must not dirty the committed store on every run);
    `--perf-attr PATH` additionally dumps the runtime-attribution
    payload (ISSUE 16) to PATH for `tools.lint --perf`;
    `--arena-compare` instead runs the ISSUE-17 equal-memory
    f32-vs-int8 KV arena comparison (bench_arena_compare)."""
    import jax

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    from singa_tpu import device, parallel

    parallel.set_mesh(None)
    device.set_default_device(device.create_tpu_device() if on_tpu
                              else device.create_cpu_device())
    if "--arena-compare" in sys.argv:
        bench_arena_compare(dev, on_tpu,
                            record="--no-record" not in sys.argv)
        return
    perf_attr = None
    if "--perf-attr" in sys.argv:
        idx = sys.argv.index("--perf-attr")
        if idx + 1 >= len(sys.argv):
            raise SystemExit("bench.py: --perf-attr needs a PATH")
        perf_attr = sys.argv[idx + 1]
    bench_serve(dev, on_tpu, record="--no-record" not in sys.argv,
                perf_attr=perf_attr)


if __name__ == "__main__":
    if "--allreduce-sub" in sys.argv:
        _allreduce_sub_main()
    elif "--quantized" in sys.argv:
        _quantized_main()
    elif "--serve" in sys.argv:
        _serve_only_main()
    elif "--sub" in sys.argv:
        _sub_main(sys.argv[sys.argv.index("--sub") + 1])
    else:
        main()
