"""Headline benchmark: Llama training throughput + MFU on one chip.

Trains the flagship decoder (models.Llama, ~110M-param `small` config on
TPU; a tiny config on CPU so the script always completes) through the
compiled-graph path — forward + backward + SGD update in ONE XLA module
with donated buffers — and reports model FLOPs utilization against the
45% target (BASELINE.json:2,5).

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

from __future__ import annotations

import json
import sys
import time

import jax
import numpy as np


# bf16 peak TFLOP/s per chip by PJRT device_kind substring.
_PEAK_TFLOPS = [
    ("v6", 918.0),       # Trillium
    ("v5p", 459.0),
    ("v5 lite", 197.0),  # v5e
    ("v5e", 197.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
]


def _peak_flops(dev) -> float:
    kind = getattr(dev, "device_kind", "").lower()
    for key, tf in _PEAK_TFLOPS:
        if key in kind:
            return tf * 1e12
    if dev.platform == "cpu":
        return 1e11  # nominal; CPU MFU is not the headline
    return 275e12  # assume v4 class


def _probe_flash(seqlen: int) -> None:
    """Compile-check the Pallas flash kernel on this backend; if Mosaic
    isn't supported here, fall back to the XLA-fused attention path
    rather than dying mid-benchmark."""
    import os

    import jax.numpy as jnp

    try:
        from singa_tpu.ops.flash_attention import flash_attention
        q = jnp.zeros((1, min(512, seqlen), 2, 64), jnp.bfloat16)
        jax.block_until_ready(
            jax.jit(lambda q: flash_attention(q, q, q, causal=True))(q))
    except Exception as e:  # pragma: no cover - backend-specific
        print(f"# flash kernel unavailable ({type(e).__name__}); "
              f"using XLA attention", file=sys.stderr)
        os.environ["SINGA_DISABLE_FLASH"] = "1"


def main() -> None:
    from singa_tpu import device, models, opt, parallel, tensor

    parallel.set_mesh(None)
    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    if on_tpu:
        _probe_flash(1024)
    if on_tpu:
        device.set_default_device(device.create_tpu_device())
        cfg = models.LlamaConfig.small()
        batch, seqlen, steps, warmup = 8, 1024, 20, 3
    else:
        device.set_default_device(device.create_cpu_device())
        cfg = models.LlamaConfig.tiny()
        batch, seqlen, steps, warmup = 4, 64, 5, 1
        cfg.max_position = max(cfg.max_position, seqlen)

    tensor.set_seed(0)
    np.random.seed(0)
    m = models.Llama(cfg)
    m.set_optimizer(opt.SGD(lr=0.01, momentum=0.9))
    ids = tensor.from_numpy(
        np.random.randint(0, cfg.vocab_size, (batch, seqlen)).astype(np.int32))
    m.compile([ids], is_train=True, use_graph=True)

    n_params = sum(int(np.prod(t.shape)) for t in m.get_params().values())

    for _ in range(warmup):
        _, loss = m.train_step(ids)
    jax.block_until_ready(loss.data)

    t0 = time.perf_counter()
    for _ in range(steps):
        _, loss = m.train_step(ids)
    jax.block_until_ready(loss.data)
    dt = time.perf_counter() - t0

    tokens = batch * seqlen * steps
    tok_per_s = tokens / dt
    # standard transformer training cost: ~6 * N FLOPs per token
    flops_per_step = 6.0 * n_params * batch * seqlen
    mfu = (flops_per_step * steps / dt) / _peak_flops(dev)

    print(json.dumps({
        "metric": "llama_train_tokens_per_sec",
        "value": round(tok_per_s, 2),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.45, 4),
    }))
    print(f"# device={dev.device_kind or dev.platform} params={n_params/1e6:.1f}M "
          f"batch={batch} seq={seqlen} step={dt/steps*1e3:.1f}ms "
          f"MFU={mfu*100:.1f}% loss={float(loss.to_numpy()):.4f}",
          file=sys.stderr)


if __name__ == "__main__":
    main()
